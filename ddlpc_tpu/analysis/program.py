"""Compiled-program contract auditor (docs/ANALYSIS.md "Program-level contracts").

PR 10's ``ddlpc-check`` proves source-tree contracts; this module audits
one level down — the programs XLA actually emits.  The perf claims the
ROADMAP's top items rest on (fused quantized collectives, ZeRO-2/3,
comm/compute overlap) are claims about *compiled* programs: which
collectives run per optimizer step, what dtype feeds the wire, whether
the ``optimization_barrier`` fences and buffer donation survive
compilation, whether a leaf declared ``P('data')`` is actually sharded
1/N (arxiv 2004.13336 and 2204.06514 both locate the silent losses
exactly here).

Mechanism: the REAL builders — ``parallel/train_step.py``'s two step
builders, ``make_update_step``, ``make_eval_step``, the serve engine's
forward builders — are lowered via ``jax.jit(...).lower(...)`` on
``ShapeDtypeStruct`` trees (the ``obs/flops.py`` eval_shape precedent:
nothing materializes, no program executes), then audited at two levels:

- **jaxpr** (``--fast``, what tier-1 runs) — collective census + fence
  count straight off the traced program, no XLA compile;
- **optimized HLO** — ``lower().compile().as_text()`` parsed by
  ``analysis/hlo.py``: the collective census XLA actually scheduled,
  per-leaf OpSharding vs the declared specs, ``input_output_alias``
  (donation ground truth) and argument/output byte totals.

Contracts checked absolutely (no baseline needed):

- ``comm-closed-form`` — the census' gradient-wire bytes equal
  ``obs/comm.comm_plan``'s closed-form counters byte-for-byte (padding
  from the ZeRO chunk layout accounted explicitly);
- ``dtype-flow`` — the operand dtype feeding each wire collective is no
  wider than the arm's declared wire dtype (int8/fp16 grads must not
  widen to fp32 before the wire on arms that claim a quantized wire);
- ``fence-survival`` — every ``apply_codec_fenced``/``_fenced_update``
  barrier the config implies is present in the jaxpr AND still present
  in the optimized HLO (compiled with XLA's late barrier-expander pass
  disabled — see :data:`FENCE_XLA_FLAG` — so the fences are countable
  after partitioning/fusion);
- ``sharding`` — per-leaf actual sharding equals the declared spec;
  silent full replication of a declared-sharded leaf reports the HBM
  bytes wasted per device;
- ``donation`` — every ``donate_argnums`` leaf is input/output-aliased
  in the compiled module (the HBM the donation was supposed to save is
  reported when it is not);
- ``stage-boundary`` (pipeline arms, ``parallel/pipeline.py``) — a
  staged forward/backward SEGMENT owns no gradient wire: its only
  collectives are the sync-BN stat pmeans (small f32 all-reduces,
  budgeted against the stage's batch-stat element count), it carries
  zero fences, and no inter-stage carry leaf leaves the stage wider
  than the model compute dtype (cross-stage dtype widening is exactly
  the regression a quantized-wire pipeline must not hide).  The
  per-stage UPDATE program is audited under all five contracts above,
  with the closed form evaluated on the stage's param subtree over the
  stage group's data-axis size.

Everything else (collective counts, argument/output bytes, entry dtype
census) is pinned by the committed per-config baseline
(``docs/analysis/program_baseline.json``, perf_gate-style staleness
stamps): a PR that adds a collective, loses a fence, or un-shards a leaf
fails ``ddlpc-check --programs`` with program + op + contract named.

Tier note: declared ``jax``-tier in ``analysis/tiers.py`` — the program
builders import the full accelerator stack — but every jax import is
function-local, so the baseline validators stay importable from jax-free
contexts (``scripts/perf_gate.py --smoke``).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from ddlpc_tpu.analysis import hlo as hlo_mod
from ddlpc_tpu.obs.comm import SCALE_BYTES, comm_plan

PyTree = Any

PROGRAM_BASELINE_SCHEMA = 1
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "docs", "analysis", "program_baseline.json",
)

# XLA runs OptimizationBarrierExpander ("cse_barrier_expander") late in
# the pipeline — AFTER the fences have done their fusion-blocking job —
# so a normally-compiled module shows zero opt-barriers even when every
# fence survived.  Disabling that one pass makes fences countable in the
# final module without changing what they fenced; the flag must be in
# XLA_FLAGS before the backend initializes (scripts/program_audit.py owns
# that), which :func:`hlo_fences_countable` verifies with a canary.
FENCE_XLA_FLAG = "--xla_disable_hlo_passes=cse_barrier_expander"

# The source files whose collectives ARE the gradient wire — everything
# else (batch-stat pmean, metric reductions, partitioner-inserted
# collectives) is auxiliary and pinned by baseline only.
_WIRE_BASENAMES = frozenset({"grad_sync.py", "compressed_allreduce.py"})

INJECTIONS = (
    "extra-collective", "fp32-widen", "drop-fence", "replicated-leaf"
)


# --------------------------------------------------------------------------
# arm registry: the audited config matrix
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Arm:
    """One audited configuration arm (codec × transport × layout)."""

    name: str
    mode: str = "none"              # none | int8 | float16
    transport: str = "simulate"     # simulate | ring
    rounding: str = "nearest"
    quantize_local: bool = True
    quantize_mean: bool = True
    shard_update: str = "off"       # off | zero1 | zero2 | zero3
    spatial: bool = False           # data×space mesh, GSPMD step
    serve_quantize: str = "off"     # serve arms only
    bucket_mb: float = 0.0          # comm/compute overlap bucket target
    pipeline_stages: int = 1        # pipe mesh axis (parallel/pipeline.py)

    @property
    def comm_variant(self) -> Optional[str]:
        if self.spatial:
            return None  # partitioner owns the collectives — baseline-pinned
        if self.transport == "ring" and self.mode != "none":
            return "ring"
        if self.shard_update == "zero2":
            return "scatter"
        if self.shard_update in ("zero1", "zero3"):
            return self.shard_update
        return "allreduce"

    def declared_wire_dtype(self, axis_size: Optional[int] = None) -> str:
        """The dtype the arm CLAIMS is on the wire.  The ring transport
        puts real quantized integers on every hop; the fused simulate
        path puts the lattice itself on the collective operand wherever
        the sums fit the narrow dtype exactly — the declaration mirrors
        ``grad_sync.simulate_wire_dtype`` (the single source of truth for
        when the fusion engages) and the HLO dtype-flow + closed-form
        contracts are what prove it.  ``axis_size`` overrides the default
        audit topology — pipeline stage groups sync over AXIS_SIZE/S
        replicas, and whether the sums fit the narrow dtype depends on
        how many replicas are summed."""
        axis_size = AXIS_SIZE if axis_size is None else axis_size
        if self.transport == "ring" and self.mode != "none":
            import jax.numpy as jnp

            from ddlpc_tpu.ops.quantize import levels_for
            from ddlpc_tpu.parallel.compressed_allreduce import wire_dtype

            comp = self.compression()
            return hlo_mod.hlo_dtype_name(
                jnp.dtype(wire_dtype(axis_size, levels_for(comp)))
            )
        if self.comm_variant in ("allreduce", "scatter", "zero1", "zero3"):
            from ddlpc_tpu.obs.comm import simulate_wire_row

            name, _ = simulate_wire_row(self.compression(), axis_size)
            return name
        return "f32"

    def compression(self):
        from ddlpc_tpu.config import CompressionConfig

        return CompressionConfig(
            mode=self.mode,
            transport=self.transport,
            rounding=self.rounding,
            quantize_local=self.quantize_local,
            quantize_mean=self.quantize_mean,
            bucket_mb=self.bucket_mb,
        )


# The audit mesh: 8 virtual CPU devices, the repo's standard collective
# test topology (tests/conftest.py).  Spatial arms split it 4×2.
AXIS_SIZE = 8
SPATIAL_DATA, SPATIAL_SPACE = 4, 2

ARMS: Dict[str, Arm] = {
    a.name: a
    for a in (
        Arm("none_simulate"),
        Arm("int8_simulate", mode="int8"),
        Arm("fp16_simulate", mode="float16"),
        Arm("int8_stochastic", mode="int8", rounding="stochastic"),
        # The ZeRO ladder (shard_update.py module docstring): the *_zero2
        # arms are PR 5's audited programs renamed with the layout
        # taxonomy fix (they persist SCATTERED grad shards — stage 2);
        # *_zero1 audits the new true stage-1 program (full-mean
        # all-reduce + chunked update + params publish), *_zero3 the
        # params-sharded gather-on-demand program.
        Arm("none_zero1", shard_update="zero1"),
        Arm("int8_zero1", mode="int8", shard_update="zero1"),
        Arm("none_zero2", shard_update="zero2"),
        Arm("int8_zero2", mode="int8", shard_update="zero2"),
        Arm("fp16_zero2", mode="float16", shard_update="zero2"),
        Arm("none_zero3", shard_update="zero3"),
        Arm("int8_zero3", mode="int8", shard_update="zero3"),
        Arm("int8_ring", mode="int8", transport="ring"),
        Arm("fp16_ring", mode="float16", transport="ring"),
        Arm("none_gspmd", spatial=True),
        Arm("fp16_gspmd", mode="float16", spatial=True, quantize_local=False),
        Arm("gspmd_zero1", spatial=True, shard_update="zero1"),
        Arm("gspmd_zero2", spatial=True, shard_update="zero2"),
        Arm("gspmd_zero3", spatial=True, shard_update="zero3"),
        # Bucketed comm/compute overlap arms: the same tiny tree split
        # into several size-targeted buckets (0.02 MiB yields B > 1 on
        # the audit model) — one fused collective per bucket, per-bucket
        # scales, and the census parity across the three layouts is what
        # pins that every layout derives the identical partition.
        Arm("int8_bucketed", mode="int8", bucket_mb=0.02),
        Arm("fp16_bucketed_zero2", mode="float16", shard_update="zero2",
            bucket_mb=0.02),
        Arm("fp16_bucketed_gspmd", mode="float16", spatial=True,
            quantize_local=False, bucket_mb=0.02),
        # MPMD pipeline arms (parallel/pipeline.py): the 8-device mesh
        # splits pipe=2 × data=4; each arm audits its per-stage
        # forward/backward segments (stage-boundary contract: no wire,
        # no widening, no fences) and per-stage update programs (the
        # full five contracts, closed form on the stage param subtree
        # over the 4-replica stage group).
        Arm("pipe2_none", pipeline_stages=2),
        Arm("pipe2_int8_zero2", mode="int8", shard_update="zero2",
            pipeline_stages=2),
        Arm("serve_fp32"),
        Arm("serve_int8", serve_quantize="int8"),
        Arm("serve_bf16", serve_quantize="bf16"),
        Arm("eval"),
        Arm("eval_gspmd", spatial=True),
    )
}

# program name -> (arm, program kind).  update_step is the cheapest
# program containing the full gradient wire, so every codec arm audits
# it; the full train step compiles on a representative subset (it adds
# the aux collectives — batch-stat pmean, metric reductions — and the
# donation/sharding of the whole state).
_TRAIN_ARMS = (
    "none_simulate", "int8_simulate", "int8_zero1", "int8_zero2",
    "int8_zero3", "int8_ring", "none_gspmd", "fp16_gspmd", "gspmd_zero1",
    "gspmd_zero2", "gspmd_zero3", "fp16_bucketed_gspmd",
)


def _program_table() -> Dict[str, Tuple[str, str]]:
    out: Dict[str, Tuple[str, str]] = {}
    for name, arm in ARMS.items():
        if name.startswith("serve_"):
            out[f"{name}/forward"] = (name, "serve_forward")
        elif name.startswith("eval"):
            out[f"{name}/eval_step"] = (name, "eval_step")
        elif arm.pipeline_stages > 1:
            # Staged MPMD programs: the driver's own per-stage segments
            # (the last stage folds forward+loss+backward into one
            # program) plus every stage's update.
            S = arm.pipeline_stages
            for s in range(S - 1):
                out[f"{name}/stage{s}_fwd"] = (name, "stage_fwd")
                out[f"{name}/stage{s}_bwd"] = (name, "stage_bwd")
            out[f"{name}/stage{S - 1}_loss_bwd"] = (name, "stage_bwd")
            for s in range(S):
                out[f"{name}/stage{s}_update"] = (name, "stage_update")
        else:
            if not arm.spatial:
                out[f"{name}/update_step"] = (name, "update_step")
            if name in _TRAIN_ARMS:
                out[f"{name}/train_step"] = (name, "train_step")
    return out


PROGRAMS: Dict[str, Tuple[str, str]] = _program_table()


def list_programs() -> List[str]:
    return sorted(PROGRAMS)


# --------------------------------------------------------------------------
# tiny experiment + aval construction (nothing materializes)
# --------------------------------------------------------------------------


def _tiny_experiment(arm: Arm):
    """The audit model/config: perf_gate's tiny shape (the cheapest
    config that exercises every layer class), with the arm's codec and
    mesh topology."""
    from ddlpc_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ParallelConfig,
        TrainConfig,
    )

    parallel = ParallelConfig(
        data_axis_size=SPATIAL_DATA if arm.spatial else -1,
        space_axis_size=SPATIAL_SPACE if arm.spatial else 1,
        pipeline_stages=arm.pipeline_stages,
    )
    return ExperimentConfig(
        model=ModelConfig(
            features=(8, 16), bottleneck_features=16, num_classes=6
        ),
        data=DataConfig(
            dataset="synthetic", image_size=(32, 32), num_classes=6,
            synthetic_len=64,
        ),
        train=TrainConfig(micro_batch_size=2, sync_period=2),
        compression=arm.compression(),
        parallel=parallel,
    )


def _abstract_state(cfg, mesh):
    """TrainState of ShapeDtypeStructs for the tiny model — the
    obs/flops.collect_convs idiom: model init under eval_shape, inputs as
    abstract arguments, zero bytes allocated."""
    import jax
    import jax.numpy as jnp

    from ddlpc_tpu.models import build_model_from_experiment
    from ddlpc_tpu.parallel.train_step import TrainState
    from ddlpc_tpu.train.optim import build_optimizer

    model = build_model_from_experiment(cfg)
    tx = build_optimizer(cfg.train)
    h, w = cfg.data.image_size
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, h, w, 3), jnp.float32),
            train=False,
        )
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=jax.eval_shape(tx.init, params),
    )
    return model, tx, state


def _chunked_opt_avals(params, opt_state):
    """The zero1 run-layout opt_state as avals: param-shaped moment
    leaves become their [N, K] chunk views (shard_update.chunk_leaf's
    shapes, computed without touching data)."""
    import jax

    from ddlpc_tpu.parallel import shard_update as zero

    pshapes = zero.param_shapes(params)

    def leaf(t):
        if not zero.chunkable(t.shape, pshapes):
            return t
        size = 1
        for d in t.shape:
            size *= int(d)
        return jax.ShapeDtypeStruct(
            (AXIS_SIZE, zero.chunk_rows(size, AXIS_SIZE)), t.dtype
        )

    return jax.tree.map(leaf, opt_state)


def _tree_elements(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        n = 1
        for d in leaf.shape:
            n *= int(d)
        total += n
    return total


def _chunk_padding_bytes(tree, n_shards: int, itemsize: int = 4) -> int:
    """Bytes the [N, K] chunk layout adds over the exact element count
    (shard_update.chunk_rows padding), per full-tree collective, at the
    collective's operand itemsize (the fused scatter pads WIRE-dtype
    elements; the params all-gather pads fp32)."""
    import jax

    from ddlpc_tpu.parallel.shard_update import chunk_rows

    pad = 0
    for leaf in jax.tree.leaves(tree):
        size = 1
        for d in leaf.shape:
            size *= int(d)
        pad += n_shards * chunk_rows(size, n_shards) - size
    return pad * itemsize


# --------------------------------------------------------------------------
# declared contracts + program bundles
# --------------------------------------------------------------------------


@dataclass
class Declared:
    """What the builders CLAIM about one program — the audit reference."""

    comm_variant: Optional[str] = None
    wire_dtype: str = "f32"
    fences: int = 0
    donated_args: Tuple[int, ...] = ()
    n_grad: int = 0
    n_param: int = 0
    axis_size: int = 1
    rs_pad_bytes: int = 0       # zero1 chunk padding on the grad scatter
    ag_pad_bytes: int = 0       # zero1 chunk padding on the params publish
    scale_collectives: int = 0  # live scalar pmaxes of the global scale(s)
    n_buckets: int = 1          # bucket_mb partition size (grad_bucket_groups)
    has_dead_norm_psum: bool = False    # jaxpr-only psum DCE'd by XLA
    # tree of per-leaf expected shard element counts (None = skip audit)
    sharding_in: Any = None
    sharding_out: Any = None
    # -- pipeline stage programs (parallel/pipeline.py) ------------------
    # Donated args that are CONSUMED, not aliased: the stage update
    # donates the stacked grad accumulator whose buffer has no
    # same-shaped output — the donation frees it for scratch reuse, so
    # requiring an input_output_alias would mislabel a real HBM win.
    donated_freed_args: Tuple[int, ...] = ()
    # stage segments: the model compute dtype every inter-stage carry
    # leaf must stay within, the output indices that cross the boundary,
    # and the stage's batch-stat element count (the sync-BN pmean budget
    # check_stage_segment holds segment collectives to).
    carry_dtype: Optional[str] = None
    carry_out_idx: Tuple[int, ...] = ()
    stats_elements: int = 0


@dataclass
class ProgramBundle:
    """A lowerable program + the avals and declared contracts to audit
    it against.  ``patch`` (injections only) is a context-manager factory
    held open across tracing/lowering — jax resolves module globals at
    TRACE time, so an injection that rewires one (e.g. neutering
    ``apply_codec_fenced``) must stay applied until the jaxpr exists."""

    name: str
    arm: Arm
    kind: str
    fn: Callable
    avals: Tuple
    declared: Declared
    patch: Optional[Callable] = None


def expected_fences(arm: Arm, kind: str, n_buckets: int = 1) -> int:
    """Barrier count the configuration implies (grad_sync.py /
    train_step.py fencing rules — the single place the expectation is
    written down, so a dropped fence is a COUNT mismatch, not a vibe).
    Every quantize stage runs once per bucket (``n_buckets`` =
    grad_bucket_groups of the audited tree), each inside its own fence
    pair: the fused wire encode keeps apply_codec_fenced's cut points
    and count, the dequantize is deliberately unfenced (one scalar
    multiply cannot FMA-contract — grad_sync._fenced_wire_encode).
    Pipeline stage SEGMENTS (stage_fwd/stage_bwd) carry zero fences —
    all codec and update fencing lives in the per-stage update, whose
    count follows the update_step rules on the stage's bucket count."""
    if kind in ("eval_step", "serve_forward", "stage_fwd", "stage_bwd"):
        return 0
    fences = 2  # _fenced_update pins the optimizer chain
    quantizing = arm.mode != "none"
    if not quantizing:
        return fences
    if arm.spatial:
        # one apply_codec_fenced on the mean gradient, per bucket
        return fences + 2 * n_buckets
    if arm.transport == "ring":
        # The N>1 ring owns its own quantized collective; no XLA-level
        # codec stages exist to fence (compressed_allreduce.py).
        return fences
    fences += n_buckets * (
        2 * int(arm.quantize_local) + 2 * int(arm.quantize_mean)
    )
    return fences


def _mesh_for(arm: Arm):
    from ddlpc_tpu.parallel.mesh import make_mesh

    cfg = _tiny_experiment(arm)
    return make_mesh(cfg.parallel)


def _shard_elems(sharding, shape) -> int:
    """Per-device elements under ``sharding``.  Uneven tilings (GSPMD
    pads them) make ``shard_shape`` raise; fall back to the HLO
    sharding's tile-assignment dims with ceil division — the padded
    shard is what lives in HBM."""
    shape = tuple(int(s) for s in shape)
    try:
        n = 1
        for d in sharding.shard_shape(shape):
            n *= int(d)
        return n
    except ValueError:
        pass
    hs = sharding._to_xla_hlo_sharding(len(shape))
    if hs.is_replicated():
        tile = [1] * len(shape)
    else:
        tile = list(hs.tile_assignment_dimensions())[: len(shape)]
    n = 1
    for d, t in zip(shape, tile):
        n *= -(-d // max(int(t), 1))
    return n


def _spec_shard_elems(mesh, spec, shape) -> int:
    """Expected per-device elements for a PartitionSpec over ``mesh`` —
    ceil division per sharded dim (GSPMD pads uneven shards; the padded
    shard is the HBM cost)."""
    shape = tuple(int(s) for s in shape)
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    n = 1
    for dim, ax in zip(shape, entries):
        if ax is None:
            n *= dim
            continue
        axes = (ax,) if isinstance(ax, str) else tuple(ax)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        n *= -(-dim // size)
    return n


def _named_tree(mesh, spec_tree, aval_tree):
    """PartitionSpec tree -> per-leaf expected shard ELEMENT counts."""
    import jax

    return jax.tree.map(
        lambda sp, av: _spec_shard_elems(mesh, sp, av.shape),
        spec_tree,
        aval_tree,
    )


def _repl_tree(aval_tree):
    import jax

    return jax.tree.map(
        lambda av: int(_aval_elems(av)), aval_tree
    )


def _aval_elems(av) -> int:
    n = 1
    for d in av.shape:
        n *= int(d)
    return n


def build_program(name: str) -> ProgramBundle:
    """Construct the jitted program + audit avals for one registry entry.

    Uses the SAME builders the trainer/bench/serve paths call — the
    auditor must audit the program that runs, not a lookalike."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    arm_name, kind = PROGRAMS[name]
    arm = ARMS[arm_name]
    cfg = _tiny_experiment(arm)
    comp = cfg.compression

    if kind == "serve_forward":
        return _build_serve(name, arm, cfg)
    if kind in ("stage_fwd", "stage_bwd", "stage_update"):
        return _build_stage_program(name, arm, kind)

    mesh = _mesh_for(arm)
    model, tx, state = _abstract_state(cfg, mesh)
    n_grad = _tree_elements(state.params)
    h, w = cfg.data.image_size

    if kind == "eval_step":
        from ddlpc_tpu.parallel.train_step import (
            make_eval_step,
            make_eval_step_gspmd,
        )

        # The trainer strips opt_state from the eval input (PR 5: no
        # per-batch all-gathers of unused moments) — audit that shape.
        eval_state = state.replace(opt_state=())
        B = AXIS_SIZE
        images = jax.ShapeDtypeStruct((B, h, w, 3), jnp.float32)
        labels = jax.ShapeDtypeStruct((B, h, w), jnp.int32)
        if arm.spatial:
            fn = make_eval_step_gspmd(model, mesh, cfg.model.num_classes)
        else:
            fn = make_eval_step(model, mesh, cfg.model.num_classes)
        img_elems, lbl_elems = _shard_elems_tree_for_batch(
            mesh, arm, images, labels
        )
        declared = Declared(
            fences=expected_fences(arm, kind),
            axis_size=mesh.shape["data"],
            sharding_in=(
                _repl_tree(eval_state), img_elems, lbl_elems
            ),
        )
        return ProgramBundle(
            name, arm, kind, fn, (eval_state, images, labels), declared
        )

    # training-side programs
    from ddlpc_tpu.parallel import shard_update as zero
    from ddlpc_tpu.parallel.train_step import (
        make_train_step,
        make_train_step_gspmd,
        make_update_step,
    )

    from ddlpc_tpu.parallel.grad_sync import grad_bucket_groups

    n_buckets = len(grad_bucket_groups(state.params, comp.bucket_mb))
    declared = Declared(
        comm_variant=arm.comm_variant,
        wire_dtype=arm.declared_wire_dtype(),
        fences=expected_fences(arm, kind, n_buckets),
        n_grad=n_grad,
        n_param=n_grad,
        axis_size=mesh.shape["data"],
        n_buckets=n_buckets,
    )
    quantizing = comp.mode != "none"
    # One live scalar pmax per global scale: the fused wire encode shares
    # its scale across replicas (per bucket), and the scatter's mean
    # stage pmaxes the chunked absmax back to the global one (per
    # bucket).  The non-fused fake-quantize stages use local scales — no
    # collective.
    # zero1 rides the allreduce path's fused wire; zero2 (scatter) and
    # zero3 ride the scatter sync — all of them share the per-bucket
    # global scale when the wire is quantized.  Ring keeps its own
    # single-scale accounting below.
    fused = declared.wire_dtype != "f32" and arm.comm_variant in (
        "allreduce", "scatter", "zero1", "zero3"
    )
    level = arm.shard_update
    if arm.comm_variant in ("allreduce", "zero1"):
        # zero1's sync IS the allreduce path's (full mean) — same fused
        # wire, same scale pmaxes; the extra all-gather carries params.
        declared.scale_collectives = n_buckets if fused else 0
    if level != "off" and not arm.spatial:
        # Every chunk layout all-gathers [1, K] param chunks (zero1/zero2
        # publish fresh params at the tail; zero3 gathers on demand at the
        # head) — fp32 chunk padding either way.
        declared.ag_pad_bytes = _chunk_padding_bytes(
            state.params, AXIS_SIZE, 4
        )
        if level in ("zero2", "zero3"):
            wire_item = hlo_mod.max_operand_itemsize(declared.wire_dtype)
            declared.rs_pad_bytes = _chunk_padding_bytes(
                state.params, AXIS_SIZE, wire_item
            )
            declared.scale_collectives = n_buckets * (
                int(fused) + int(quantizing and comp.quantize_mean)
            )
            declared.has_dead_norm_psum = True
    if arm.comm_variant == "ring":
        declared.scale_collectives = 1

    if kind == "update_step":
        fn = make_update_step(
            tx, mesh, comp, shard_update=level,
            seed=cfg.train.seed,
        )
        opt_avals = state.opt_state
        opt_spec = jax.tree.map(lambda _: P(), opt_avals)
        param_in_avals = state.params
        param_in_elems = _repl_tree(state.params)
        if level != "off":
            opt_avals = _chunked_opt_avals(state.params, state.opt_state)
            # opt_partition_specs is written over the FULL-layout template;
            # the chunk view replaces leaves 1:1, so the spec tree remaps
            # structurally (chunked leaves: P('data') on chunk axis 0).
            opt_spec = _respec_chunked(
                zero.opt_partition_specs(tx, state.params, level, "data"),
                opt_avals,
            )
        if level == "zero3":
            # zero3's update consumes AND produces chunked params; the
            # full model never appears in this program at all.
            param_in_avals = _chunked_opt_avals(state.params, state.params)
            param_in_elems = _named_tree(
                mesh,
                jax.tree.map(lambda _: P("data"), param_in_avals),
                param_in_avals,
            )
            # No params all-gather in the update program (the train step's
            # gather-on-demand prologue owns it) — wire is the RS alone.
            declared.comm_variant = "zero3_update"
            declared.ag_pad_bytes = 0
        avals = (param_in_avals, opt_avals, state.params)
        grad_elems = _repl_tree(state.params)
        opt_elems = _named_tree(mesh, opt_spec, opt_avals)
        declared.donated_args = (0, 1)
        declared.sharding_in = (param_in_elems, opt_elems, grad_elems)
        declared.sharding_out = (param_in_elems, opt_elems)
        # update-only program keeps the dead norm psum only on zero2
        # (train_step._apply_update_sharded): zero1's optax.global_norm
        # is collective-free, and make_update_step's zero3 branch goes
        # straight from scatter to the fenced update — no norm at all.
        declared.has_dead_norm_psum = level == "zero2"
        return ProgramBundle(name, arm, kind, fn, avals, declared)

    # train_step
    A, B = cfg.train.sync_period, cfg.train.micro_batch_size * AXIS_SIZE
    images = jax.ShapeDtypeStruct((A, B, h, w, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((A, B, h, w), jnp.int32)
    if arm.spatial:
        fn = make_train_step_gspmd(
            model, tx, mesh, comp, shard_update=level,
            seed=cfg.train.seed,
        )
        if level != "off":
            fn = fn.build_for(state)  # the lowerable inner jit
        state_avals = state
        opt_layout = zero.GSPMD_LAYOUT_FOR_LEVEL.get(level)
    else:
        fn = make_train_step(
            model, tx, mesh, comp, shard_update=level,
            seed=cfg.train.seed, param_avals=state.params,
        )
        state_avals = state
        opt_layout = None
        if level != "off":
            state_avals = state.replace(
                opt_state=_chunked_opt_avals(state.params, state.opt_state)
            )
            if level == "zero3":
                # Run-layout params: [N, K] chunks, P('data') on axis 0.
                state_avals = state_avals.replace(
                    params=_chunked_opt_avals(state.params, state.params)
                )
            opt_layout = level
    declared.donated_args = (0,)
    declared.has_dead_norm_psum = False  # the norm psum is live here
    declared.sharding_in = (
        _train_state_shard_tree(mesh, arm, tx, state, state_avals, opt_layout),
        _batch_shard_elems(mesh, arm, images),
        _batch_shard_elems(mesh, arm, labels),
    )
    declared.sharding_out = None  # metrics tree varies; inputs carry the claim
    return ProgramBundle(
        name, arm, kind, fn, (state_avals, images, labels), declared
    )


# --------------------------------------------------------------------------
# pipeline stage programs (parallel/pipeline.py)
# --------------------------------------------------------------------------

# One driver per pipeline arm, built lazily and kept for the process —
# every stage program of the arm lowers out of the SAME driver instance
# (the real programs the schedule dispatches, not lookalikes), and the
# tiny-model state it splits is materialized once, not per program.
_PIPE_CACHE: Dict[str, Tuple] = {}


def _pipe_driver(arm: Arm):
    """(cfg, driver, placed PipelineState) for a pipeline arm on the
    tiny model.  Unlike the flat arms this MATERIALIZES the tiny state:
    the driver's ``init_state`` is the only code path that builds the
    stage plan, splits params/stats/opt and constructs the per-stage
    jitted programs — auditing anything else would audit a fork."""
    if arm.name in _PIPE_CACHE:
        return _PIPE_CACHE[arm.name]
    import jax

    from ddlpc_tpu.models import build_model_from_experiment
    from ddlpc_tpu.parallel.pipeline import make_pipeline_train_step
    from ddlpc_tpu.parallel.train_step import create_train_state
    from ddlpc_tpu.train.optim import build_optimizer

    cfg = _tiny_experiment(arm)
    mesh = _mesh_for(arm)
    model = build_model_from_experiment(cfg)
    tx = build_optimizer(cfg.train)
    h, w = cfg.data.image_size
    state = create_train_state(model, tx, jax.random.key(0), (1, h, w, 3))
    drv = make_pipeline_train_step(
        model, tx, mesh, cfg.compression,
        n_microbatches=cfg.train.sync_period,
        shard_update=arm.shard_update, seed=cfg.train.seed,
    )
    pstate = drv.init_state(state)
    _PIPE_CACHE[arm.name] = (cfg, drv, pstate)
    return _PIPE_CACHE[arm.name]


def _avals_of(tree):
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
    )


def _build_stage_program(name: str, arm: Arm, kind: str) -> ProgramBundle:
    """Bundle one of the pipeline driver's per-stage programs.  Segments
    (stage_fwd / stage_bwd / the last stage's fused loss_bwd) get the
    stage-boundary contract — zero fences, stat-sync-only collectives,
    carry leaves no wider than the model compute dtype; the per-stage
    update gets the full update_step treatment with the closed form on
    the stage param subtree at the stage group's axis size."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ddlpc_tpu.parallel import shard_update as zero
    from ddlpc_tpu.parallel.grad_sync import grad_bucket_groups

    cfg, drv, pstate = _pipe_driver(arm)
    seg = name.rsplit("/", 1)[1]            # e.g. "stage0_fwd"
    s = int(seg[len("stage"):].split("_", 1)[0])
    S, nd = drv.n_stages, drv._n_data
    mesh_s = drv._meshes[s]
    h, w = cfg.data.image_size
    B = cfg.train.micro_batch_size * nd     # one global microbatch
    params_av = _avals_of(drv._p_split[s])
    stats_av = _avals_of(drv._s_split[s])
    carries = drv.carry_avals((B, h, w, 3))
    gacc_av = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct((nd,) + tuple(a.shape), jnp.float32),
        params_av,
    )
    carry_name = hlo_mod.hlo_dtype_name(jnp.dtype(drv.model.dtype))

    def data_tree(tree):
        return _named_tree(
            mesh_s, jax.tree.map(lambda _: P("data"), tree), tree
        )

    cin_av = (
        jax.ShapeDtypeStruct((B, h, w, 3), jnp.float32)
        if s == 0
        else carries[s - 1]
    )
    declared = Declared(
        fences=expected_fences(arm, kind),
        axis_size=nd,
        stats_elements=_tree_elements(stats_av),
        carry_dtype=carry_name,
    )

    if kind == "stage_fwd":
        declared.carry_out_idx = (0,)
        declared.sharding_in = (
            _repl_tree(params_av), _repl_tree(stats_av), data_tree(cin_av)
        )
        declared.sharding_out = (data_tree(carries[s]), _repl_tree(stats_av))
        return ProgramBundle(
            name, arm, kind, drv._fwd[s],
            (params_av, stats_av, cin_av), declared,
        )

    if kind == "stage_bwd":
        if seg.endswith("loss_bwd"):
            # Last stage: forward + loss + backward in one program; loss
            # and pixel-acc leave stacked per replica (host averages) so
            # the segment stays inside the stage-boundary contract.
            labels_av = jax.ShapeDtypeStruct((B, h, w), jnp.int32)
            declared.carry_out_idx = (2,)   # dcin back to stage s-1
            declared.donated_args = (4,)    # gacc
            declared.sharding_in = (
                _repl_tree(params_av), _repl_tree(stats_av),
                data_tree(cin_av), data_tree(labels_av), data_tree(gacc_av),
            )
            declared.sharding_out = (
                1, 1, data_tree(carries[s - 1]), _repl_tree(stats_av),
                data_tree(gacc_av),
            )
            return ProgramBundle(
                name, arm, kind, drv._bwd[s],
                (params_av, stats_av, cin_av, labels_av, gacc_av), declared,
            )
        dout_av = carries[s]
        declared.donated_args = (4,)
        # Stage 0's carry cotangent is a scalar stub (nothing upstream
        # consumes it) — only interior stages ship a real dcin.
        declared.carry_out_idx = () if s == 0 else (0,)
        dcin_elems = 1 if s == 0 else data_tree(carries[s - 1])
        declared.sharding_in = (
            _repl_tree(params_av), _repl_tree(stats_av),
            data_tree(cin_av), data_tree(dout_av), data_tree(gacc_av),
        )
        declared.sharding_out = (dcin_elems, data_tree(gacc_av))
        return ProgramBundle(
            name, arm, kind, drv._bwd[s],
            (params_av, stats_av, cin_av, dout_av, gacc_av), declared,
        )

    # stage_update: the exact make_update_step wire + fenced update on
    # the stage's param subtree, within the nd-replica stage group.
    st_av = _avals_of(pstate.stages[s])
    comp = arm.compression()
    n_grad = _tree_elements(params_av)
    n_buckets = len(grad_bucket_groups(drv._p_split[s], comp.bucket_mb))
    level = drv._level
    declared.comm_variant = arm.comm_variant
    declared.wire_dtype = arm.declared_wire_dtype(axis_size=nd)
    declared.fences = expected_fences(arm, kind, n_buckets)
    declared.n_grad = n_grad
    declared.n_param = n_grad
    declared.n_buckets = n_buckets
    declared.donated_args = (0, 1, 2)
    declared.donated_freed_args = (2,)  # gacc: consumed, no alias target
    declared.carry_dtype = None         # no carry leaves this program
    quantizing = comp.mode != "none"
    fused = declared.wire_dtype != "f32" and arm.comm_variant in (
        "allreduce", "scatter", "zero1"
    )
    if arm.comm_variant == "allreduce":
        declared.scale_collectives = n_buckets if fused else 0
    param_elems = _repl_tree(params_av)
    opt_elems = _repl_tree(st_av.opt_state)
    if level != "off":
        declared.ag_pad_bytes = _chunk_padding_bytes(params_av, nd, 4)
        if level == "zero2":
            wire_item = hlo_mod.max_operand_itemsize(declared.wire_dtype)
            declared.rs_pad_bytes = _chunk_padding_bytes(
                params_av, nd, wire_item
            )
            declared.scale_collectives = n_buckets * (
                int(fused) + int(quantizing and comp.quantize_mean)
            )
        opt_spec = _respec_chunked(
            zero.opt_partition_specs(drv.tx, drv._p_split[s], level, "data"),
            st_av.opt_state,
        )
        opt_elems = _named_tree(mesh_s, opt_spec, st_av.opt_state)
    step_av = jax.ShapeDtypeStruct((), jnp.int32)
    declared.sharding_in = (
        param_elems, opt_elems, data_tree(gacc_av), _repl_tree(stats_av), 1
    )
    declared.sharding_out = (
        param_elems, opt_elems, _repl_tree(stats_av), 1, 1
    )
    avals = (st_av.params, st_av.opt_state, gacc_av, st_av.batch_stats,
             step_av)
    return ProgramBundle(name, arm, kind, drv._upd[s], avals, declared)


def _respec_chunked(spec_tree, chunked_avals):
    """zero1 opt specs are written against the full-layout template;
    remap them structurally onto the chunked aval tree (identical
    treedef, leaf-for-leaf)."""
    import jax

    leaves_spec = jax.tree_util.tree_leaves(
        spec_tree, is_leaf=lambda x: x is None
    )
    treedef = jax.tree_util.tree_structure(chunked_avals)
    return jax.tree_util.tree_unflatten(treedef, leaves_spec)


def _shard_elems_tree_for_batch(mesh, arm: Arm, images, labels):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = (
        P("data", "space") if arm.spatial else P("data")
    )
    return tuple(
        _shard_elems(NamedSharding(mesh, spec), av.shape)
        for av in (images, labels)
    )


def _batch_shard_elems(mesh, arm: Arm, av):
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = (
        P(None, "data", "space") if arm.spatial else P(None, "data")
    )
    return _shard_elems(NamedSharding(mesh, spec), av.shape)


def _train_state_shard_tree(mesh, arm, tx, state, state_avals, opt_layout):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddlpc_tpu.parallel import shard_update as zero

    param_elems = _repl_tree(state_avals.params)
    if opt_layout is None:
        opt_elems = _repl_tree(state_avals.opt_state)
    elif opt_layout in zero.CHUNK_LAYOUTS:
        spec = zero.opt_partition_specs(tx, state.params, opt_layout, "data")
        spec = _respec_chunked(spec, state_avals.opt_state)
        opt_elems = _named_tree(mesh, spec, state_avals.opt_state)
        if opt_layout == "zero3":
            # state_avals.params are the [N, K] chunk views, P('data').
            param_elems = _named_tree(
                mesh,
                jax.tree.map(lambda _: P("data"), state_avals.params),
                state_avals.params,
            )
    else:  # gspmd family
        spec = zero.opt_partition_specs(
            tx, state.params, opt_layout, "data",
            n_shards=mesh.shape["data"],
        )
        opt_elems = _named_tree(mesh, spec, state_avals.opt_state)
        if opt_layout == "gspmd_zero3":
            # Params keep their shapes; the rule engine shards a dim
            # (even_shard_spec) or keeps the leaf replicated-by-rule.
            pd = zero.param_decisions(
                state.params, opt_layout, mesh.shape["data"], "data"
            )
            param_elems = _named_tree(
                mesh,
                jax.tree.map(lambda d: d.spec, pd),
                state_avals.params,
            )
    return state_avals.replace(
        step=_aval_elems(state_avals.step),
        params=param_elems,
        batch_stats=_repl_tree(state_avals.batch_stats),
        opt_state=opt_elems,
    )


def _build_serve(name: str, arm: Arm, cfg) -> ProgramBundle:
    """The serve engine's forward program — the builders the engine's jit
    cache holds (train_step.make_logits_fn / serve.quantized's fused
    dequant), on one power-of-two bucket of the tile geometry."""
    import jax
    import jax.numpy as jnp

    from ddlpc_tpu.models import build_model
    from ddlpc_tpu.parallel.train_step import TrainState, make_logits_fn
    from ddlpc_tpu.serve import quantized as q
    from ddlpc_tpu.train.optim import build_optimizer

    model = build_model(cfg.model, norm_axis_name=None)
    tx = build_optimizer(cfg.train, total_steps=1)
    h, w = cfg.data.image_size
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, h, w, 3), jnp.float32),
            train=False,
        )
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    images = jax.ShapeDtypeStruct((4, h, w, 3), jnp.float32)
    declared = Declared(fences=0)
    if arm.serve_quantize == "off":
        state = TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=jax.eval_shape(tx.init, params),
        )
        fn = make_logits_fn(model)
        return ProgramBundle(name, arm, "serve_forward", fn, (state, images),
                             declared)
    wire = jnp.int8 if arm.serve_quantize == "int8" else jnp.bfloat16
    qstate = q.QuantizedState(
        params=jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape, wire), params
        ),
        scales=jax.tree.map(
            lambda p: jax.ShapeDtypeStruct((), jnp.float32), params
        ),
        batch_stats=batch_stats,
    )
    fn = q.make_quantized_logits_fn(model, arm.serve_quantize)
    return ProgramBundle(
        name, arm, "serve_forward", fn, (qstate, images), declared
    )


# --------------------------------------------------------------------------
# audits
# --------------------------------------------------------------------------


@dataclass
class ProgramViolation:
    program: str
    contract: str
    message: str

    def format(self) -> str:
        return f"VIOLATION {self.program}: [{self.contract}] {self.message}"


@dataclass
class ProgramAudit:
    """Everything the auditor measured about one program."""

    name: str
    arm: str
    kind: str
    jaxpr_census: List[Dict[str, object]] = field(default_factory=list)
    jaxpr_fences: int = 0
    # full-mode fields (None when --fast)
    hlo_census: Optional[List[Dict[str, object]]] = None
    hlo_fences: Optional[int] = None          # -1 = expander active
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    aliased_bytes: Optional[int] = None
    donated_bytes: Optional[int] = None
    donated_leaves: Optional[int] = None
    aliased_leaves: Optional[int] = None
    param_dtypes: Optional[Dict[str, int]] = None
    sharded_in_leaves: Optional[int] = None
    sharded_out_leaves: Optional[int] = None
    violations: List[ProgramViolation] = field(default_factory=list)

    def baseline_entry(self) -> Dict[str, object]:
        entry: Dict[str, object] = {
            "jaxpr": {
                "census": self.jaxpr_census,
                "fences": self.jaxpr_fences,
            }
        }
        if self.hlo_census is not None:
            entry["hlo"] = {
                "census": self.hlo_census,
                "fences": self.hlo_fences,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "aliased_bytes": self.aliased_bytes,
                "donated_bytes": self.donated_bytes,
                "donated_leaves": self.donated_leaves,
                "aliased_leaves": self.aliased_leaves,
                "param_dtypes": self.param_dtypes,
                "sharded_in_leaves": self.sharded_in_leaves,
                "sharded_out_leaves": self.sharded_out_leaves,
            }
        return entry

    def to_record(self) -> Dict[str, object]:
        rec: Dict[str, object] = {
            "kind": "program",
            "program": self.name,
            "arm": self.arm,
            "program_kind": self.kind,
            "jaxpr_fences": self.jaxpr_fences,
            "jaxpr_census": _census_strings(self.jaxpr_census),
            "violations": len(self.violations),
        }
        if self.hlo_census is not None:
            rec.update(
                hlo_fences=self.hlo_fences,
                hlo_census=_census_strings(self.hlo_census),
                argument_bytes=self.argument_bytes,
                output_bytes=self.output_bytes,
                aliased_bytes=self.aliased_bytes,
                donated_bytes=self.donated_bytes,
            )
        return rec


def _census_strings(rows: List[Dict[str, object]]) -> List[str]:
    return [
        f"{r['kind']}|{r['dtype']}|{r.get('group', 'all')}|"
        f"count={r['count']}|elements={r['elements']}|bytes={r['bytes']}"
        for r in rows
    ]


_FENCE_CANARY: Dict[str, bool] = {}


def hlo_fences_countable() -> bool:
    """True when the backend keeps ``opt-barrier`` in the final module
    (the barrier-expander pass was disabled before backend init — the
    program_audit CLI does this).  Checked once per process with a
    two-barrier canary program."""
    if "ok" not in _FENCE_CANARY:
        import jax
        import jax.numpy as jnp
        from jax import lax

        def canary(x):
            return lax.optimization_barrier(
                lax.optimization_barrier(x) * 2
            )

        text = (
            jax.jit(canary)
            .lower(jax.ShapeDtypeStruct((4,), jnp.float32))
            .compile()
            .as_text()
        )
        _FENCE_CANARY["ok"] = (
            hlo_mod.parse_hlo_module(text).fence_count == 2
        )
    return _FENCE_CANARY["ok"]


def _classify_wire(arm: Arm):
    def classify(op: hlo_mod.HloOp) -> str:
        base = os.path.basename(op.source_file)
        if base in _WIRE_BASENAMES:
            return "wire"
        if (
            arm.shard_update != "off"
            and not arm.spatial
            and op.opcode.startswith("all-gather")
            and base in ("train_step.py", "shard_update.py")
        ):
            # The chunk layouts' params all-gather: zero1/zero2 publish
            # fresh params at the tail, zero3 gathers on demand at the
            # step head — wire either way.
            return "wire"
        return "aux"

    return classify


def check_comm_closed_form(
    bundle: ProgramBundle, rows: List[Dict[str, object]], level: str
) -> List[ProgramViolation]:
    """The census' gradient-wire rows vs ``obs/comm.comm_plan`` —
    byte-for-byte, with the ZeRO chunk padding and the scalar control
    collectives (global-scale pmax, the jaxpr-level dead norm psum)
    accounted explicitly.  ``rows`` must already be restricted to the
    wire (HLO: group == "wire"; jaxpr: the update program's census IS the
    wire plus the declared scalars)."""
    d = bundle.declared
    if d.comm_variant is None:
        return []
    comp = bundle.arm.compression()
    plan = comm_plan(
        d.n_grad, d.n_param, comp, d.axis_size, d.comm_variant,
        n_buckets=d.n_buckets,
    )
    expected: Dict[Tuple[str, str], int] = {}
    if d.comm_variant in (
        "allreduce", "scatter", "zero1", "zero3", "zero3_update"
    ):
        # The plan's bytes_wire is payload + one fp32 scale per bucket;
        # in the program those are SEPARATE collectives — the narrow
        # payload reduce and the scalar scale pmax(es), the latter
        # accounted in scalar_bytes below.
        row = plan[0]
        wire = str(row["wire_dtype"])
        scale_in_wire = 0 if wire == "f32" else SCALE_BYTES * d.n_buckets
        payload = int(row["bytes_wire"]) - scale_in_wire
        if d.comm_variant == "allreduce":
            expected[("all-reduce", wire)] = payload
        elif d.comm_variant == "zero1":
            # Full-mean all-reduce + the chunked update's params publish.
            expected[("all-reduce", wire)] = payload
            expected[("all-gather", "f32")] = (
                int(plan[1]["bytes_wire"]) + d.ag_pad_bytes
            )
        else:
            expected[("reduce-scatter", wire)] = payload + d.rs_pad_bytes
            if d.comm_variant != "zero3_update":
                expected[("all-gather", "f32")] = (
                    int(plan[1]["bytes_wire"]) + d.ag_pad_bytes
                )
    elif d.comm_variant == "ring":
        expected[("collective-permute", d.wire_dtype)] = plan[0]["bytes_post"]
    scalar_bytes = SCALE_BYTES * d.scale_collectives
    if d.has_dead_norm_psum and level == "jaxpr":
        scalar_bytes += 4  # psum of the f32[] grad-norm partial (DCE'd by XLA)
    if scalar_bytes:
        expected[("all-reduce", "f32")] = (
            expected.get(("all-reduce", "f32"), 0) + scalar_bytes
        )
    actual: Dict[Tuple[str, str], int] = {}
    for r in rows:
        key = (str(r["kind"]), str(r["dtype"]))
        actual[key] = actual.get(key, 0) + int(r["bytes"])
    out: List[ProgramViolation] = []
    for key in sorted(set(expected) | set(actual)):
        kind, dtype = key
        exp, act = expected.get(key, 0), actual.get(key, 0)
        if exp != act:
            out.append(
                ProgramViolation(
                    bundle.name, "comm-closed-form",
                    f"{level} census {kind}[{dtype}] moves {act} B/replica/"
                    f"step but obs/comm.comm_plan's closed form says {exp} B "
                    f"(variant={d.comm_variant}, codec={comp.mode}) — the "
                    f"program and the accounting have drifted",
                )
            )
    return out


def check_dtype_flow(
    bundle: ProgramBundle, rows: List[Dict[str, object]], level: str
) -> List[ProgramViolation]:
    """No wire collective may be fed a dtype wider than the arm declares.

    Scalar control collectives (the global-scale pmaxes, the grad-norm
    psum) are exempt — they are not the gradient payload; XLA's
    all-reduce combiner may merge several of them into one op, so the
    exemption budget is the DECLARED scalar count, not ops-in-row.  On
    arms that declare a quantized wire (ring, and the fused simulate
    path), an fp32 operand here is exactly the "int8 grads widened to
    fp32 before the wire" regression this contract exists to catch."""
    d = bundle.declared
    if d.comm_variant is None:
        return []
    declared_bytes = hlo_mod.max_operand_itemsize(d.wire_dtype)
    scalar_budget = d.scale_collectives + int(d.has_dead_norm_psum)
    out: List[ProgramViolation] = []
    for r in rows:
        if r["kind"] not in (
            "all-reduce", "reduce-scatter", "collective-permute"
        ):
            continue
        if int(r["elements"]) <= max(int(r["count"]), scalar_budget):
            continue  # scalar control collective(s)
        width = hlo_mod.max_operand_itemsize(str(r["dtype"]))
        if width > declared_bytes:
            out.append(
                ProgramViolation(
                    bundle.name, "dtype-flow",
                    f"{level} {r['kind']} wire operand is {r['dtype']} "
                    f"({width} B/elt), wider than the declared wire dtype "
                    f"{d.wire_dtype} ({declared_bytes} B/elt) — quantized "
                    f"gradients widened before the wire "
                    f"({r['elements']} elements)",
                )
            )
    return out


def check_stage_segment(
    bundle: ProgramBundle, rows: List[Dict[str, object]], level: str
) -> List[ProgramViolation]:
    """The stage-boundary collective contract: a pipeline segment owns
    no gradient wire.  Its only admissible collectives are the sync-BN
    stat pmeans — small f32 all-reduces whose total element count is
    budgeted at 4× the stage's batch-stat elements (forward pmeans the
    fresh mean/var per norm layer; the backward recompute re-runs them
    and their transposes — still stat-shaped).  Anything else (a
    reduce-scatter, an all-gather, a permute, or an all-reduce moving a
    gradient-sized payload) means gradient traffic leaked out of the
    stage update and into a segment."""
    d = bundle.declared
    out: List[ProgramViolation] = []
    budget = 4 * d.stats_elements
    total = 0
    for r in rows:
        if r["kind"] != "all-reduce" or str(r["dtype"]) != "f32":
            out.append(
                ProgramViolation(
                    bundle.name, "stage-boundary",
                    f"{level} census has {r['kind']}[{r['dtype']}] inside "
                    f"a pipeline stage segment — segments own no gradient "
                    f"wire; every collective belongs to the stage update "
                    f"and the only cross-stage traffic is the host-driven "
                    f"carry send",
                )
            )
            continue
        total += int(r["elements"])
    if total > budget:
        out.append(
            ProgramViolation(
                bundle.name, "stage-boundary",
                f"{level} segment all-reduces move {total} f32 elements, "
                f"over the sync-BN stat budget {budget} (4 × "
                f"{d.stats_elements} stage batch-stat elements) — a "
                f"gradient-sized payload leaked into a stage segment",
            )
        )
    return out


def check_carry_dtypes(
    bundle: ProgramBundle, out_shape
) -> List[ProgramViolation]:
    """No inter-stage carry leaf may leave a segment wider than the
    model compute dtype (``declared.carry_dtype``): the carry is the
    stage boundary's whole payload, and silently promoting it to f32
    doubles the activation-send and GPipe-stash bytes the HBM pricing
    (obs/hbm.py) and the A/B's claims rest on."""
    import jax

    d = bundle.declared
    if d.carry_dtype is None or not d.carry_out_idx:
        return []
    limit = hlo_mod.max_operand_itemsize(d.carry_dtype)
    outs = out_shape if isinstance(out_shape, (tuple, list)) else (out_shape,)
    out: List[ProgramViolation] = []
    for idx in d.carry_out_idx:
        for leaf in jax.tree_util.tree_leaves(outs[idx]):
            dt = hlo_mod.hlo_dtype_name(leaf.dtype)
            if hlo_mod.max_operand_itemsize(dt) > limit:
                out.append(
                    ProgramViolation(
                        bundle.name, "stage-boundary",
                        f"inter-stage carry leaf {dt}{list(leaf.shape)} is "
                        f"wider than the declared boundary dtype "
                        f"{d.carry_dtype} — cross-stage dtype widening "
                        f"(output {idx})",
                    )
                )
    return out


def _jaxpr_wire_rows(
    bundle: ProgramBundle, census: List[Dict[str, object]]
) -> Optional[List[Dict[str, object]]]:
    """jaxpr census rows usable for the comm/dtype checks.  Only the
    update program's census is pure wire (train/eval programs interleave
    batch-stat and metric collectives, which only HLO metadata can
    separate; the pipeline stage_update pmeans stats and keeps the norm
    psum live, so its wire checks run on the HLO census' classified
    rows)."""
    if bundle.kind != "update_step":
        return None
    return census


def audit_program(
    name: str,
    fast: bool = True,
    bundle: Optional[ProgramBundle] = None,
) -> ProgramAudit:
    """Lower (and in full mode compile) one registry program and run
    every absolute contract check.  ``bundle`` override is the injection
    hook (scripts/program_audit.py --inject)."""
    import contextlib

    if bundle is None:
        bundle = build_program(name)
    audit = ProgramAudit(name=bundle.name, arm=bundle.arm.name,
                         kind=bundle.kind)
    stack = contextlib.ExitStack()
    if bundle.patch is not None:
        # keep the patch applied through tracing AND lowering/compile
        stack.enter_context(bundle.patch())
    with stack:
        return _audit_traced(bundle, audit, fast)


def _audit_traced(bundle, audit: ProgramAudit, fast: bool) -> ProgramAudit:
    import jax

    traced = jax.make_jaxpr(lambda *a: bundle.fn(*a), return_shape=True)
    jaxpr, out_shape = traced(*bundle.avals)
    audit.jaxpr_census = hlo_mod.census_to_dicts(
        hlo_mod.jaxpr_collectives(jaxpr)
    )
    audit.jaxpr_fences = hlo_mod.jaxpr_fence_count(jaxpr)

    d = bundle.declared
    if audit.jaxpr_fences != d.fences:
        audit.violations.append(
            ProgramViolation(
                bundle.name, "fence-survival",
                f"jaxpr carries {audit.jaxpr_fences} optimization_barrier "
                f"fence(s) but the codec/update fencing rules imply "
                f"{d.fences} (apply_codec_fenced/_fenced_update dropped?)",
            )
        )
    wire_rows = _jaxpr_wire_rows(bundle, audit.jaxpr_census)
    if wire_rows is not None:
        audit.violations.extend(
            check_comm_closed_form(bundle, wire_rows, "jaxpr")
        )
        audit.violations.extend(
            check_dtype_flow(bundle, wire_rows, "jaxpr")
        )
    if bundle.kind in ("stage_fwd", "stage_bwd"):
        audit.violations.extend(
            check_stage_segment(bundle, audit.jaxpr_census, "jaxpr")
        )
        audit.violations.extend(check_carry_dtypes(bundle, out_shape))
    if fast:
        return audit

    lowered = bundle.fn.lower(*bundle.avals)
    compiled = lowered.compile()
    module = hlo_mod.parse_hlo_module(compiled.as_text())
    classify = _classify_wire(bundle.arm)
    audit.hlo_census = hlo_mod.census_to_dicts(
        hlo_mod.hlo_collective_census(module.ops, classify)
    )
    audit.hlo_fences = (
        module.fence_count if hlo_fences_countable() else -1
    )
    audit.argument_bytes = sum(s.bytes for s in module.entry_params)
    audit.output_bytes = sum(s.bytes for s in module.entry_outputs)
    dtypes: Dict[str, int] = {}
    for s in module.entry_params:
        dtypes[s.dtype] = dtypes.get(s.dtype, 0) + 1
    audit.param_dtypes = dtypes

    if audit.hlo_fences >= 0 and audit.hlo_fences != d.fences:
        audit.violations.append(
            ProgramViolation(
                bundle.name, "fence-survival",
                f"optimized HLO carries {audit.hlo_fences} opt-barrier "
                f"fence(s), expected {d.fences} — a fence the jaxpr had "
                f"did not survive compilation",
            )
        )
    hlo_wire = [r for r in audit.hlo_census if r.get("group") == "wire"]
    audit.violations.extend(check_comm_closed_form(bundle, hlo_wire, "hlo"))
    audit.violations.extend(check_dtype_flow(bundle, hlo_wire, "hlo"))
    if bundle.kind in ("stage_fwd", "stage_bwd"):
        audit.violations.extend(
            check_stage_segment(bundle, audit.hlo_census, "hlo")
        )
    _audit_donation(bundle, compiled, module, audit)
    _audit_sharding(bundle, compiled, audit, out_shape)
    return audit


def _kept_leaf_params(bundle, compiled):
    """Align the lowered aval leaves with the compiled module's entry
    parameters.  ``compiled.input_shardings`` mirrors the args tree with
    ``None`` at PRUNED (unused, ``keep_unused=False``) leaves, so the
    non-None leaves in flatten order correspond 1:1 to entry parameters
    0..P-1 — no shape matching needed (entry shapes are per-device under
    SPMD, the avals are global).

    Returns (flat_idx -> param_number, flat avals, flat shardings,
    per-arg leaf spans)."""
    import jax

    avals_flat = jax.tree_util.tree_leaves(bundle.avals)
    shardings_flat = _flatten_with_none(compiled.input_shardings[0])
    mapping: Dict[int, int] = {}
    p = 0
    for i, sh in enumerate(shardings_flat):
        if sh is not None:
            mapping[i] = p
            p += 1
    spans = []
    offset = 0
    for a in bundle.avals:
        n = len(jax.tree_util.tree_leaves(a))
        spans.append((offset, offset + n))
        offset += n
    return mapping, avals_flat, shardings_flat, spans


def _audit_donation(bundle, compiled, module, audit: ProgramAudit) -> None:
    """Every donate_argnums leaf must be input/output-aliased in the
    compiled module; a donated-but-unaliased buffer is HBM the donation
    was supposed to save (reported in bytes)."""
    d = bundle.declared
    mapping, avals_flat, shardings_flat, spans = _kept_leaf_params(
        bundle, compiled
    )
    if len(mapping) != len(module.entry_params):
        audit.violations.append(
            ProgramViolation(
                bundle.name, "donation",
                f"cannot align avals with entry parameters "
                f"({len(mapping)} kept leaves vs "
                f"{len(module.entry_params)} entry params) — auditor "
                f"assumption broken, treat as drift",
            )
        )
        return
    aliased_params = set(module.aliases.values())
    donated_bytes = aliased_bytes = 0
    donated_leaves = aliased_leaves = 0
    for arg_idx in d.donated_args:
        lo, hi = spans[arg_idx]
        for flat_idx in range(lo, hi):
            leaf = avals_flat[flat_idx]
            leaf_bytes = hlo_mod.shape_bytes(
                hlo_mod.hlo_dtype_name(leaf.dtype),
                tuple(int(x) for x in leaf.shape),
            )
            donated_leaves += 1
            donated_bytes += leaf_bytes
            p = mapping.get(flat_idx)
            if p is None:
                continue  # pruned (unused) donated leaf: jax frees it
            if p in aliased_params:
                aliased_leaves += 1
                aliased_bytes += module.entry_params[p].bytes
            elif arg_idx in d.donated_freed_args:
                # Consumed-not-aliased by declaration (e.g. the stage
                # update's stacked grad accumulator: no same-shaped
                # output exists; the donation frees the buffer for
                # scratch reuse, which is the intended HBM win).
                continue
            else:
                audit.violations.append(
                    ProgramViolation(
                        bundle.name, "donation",
                        f"donated input leaf (arg {arg_idx}, "
                        f"{leaf.dtype}{list(leaf.shape)}) is NOT "
                        f"input/output-aliased in the compiled module — "
                        f"{leaf_bytes} B of HBM the donation was supposed "
                        f"to save",
                    )
                )
    if not d.donated_args and module.aliases:
        audit.violations.append(
            ProgramViolation(
                bundle.name, "donation",
                f"program declares no donation but the compiled module "
                f"aliases params {sorted(module.aliases.values())} — "
                f"donation semantics drifted",
            )
        )
    audit.donated_bytes = donated_bytes
    audit.donated_leaves = donated_leaves
    audit.aliased_bytes = aliased_bytes
    audit.aliased_leaves = aliased_leaves


def _flatten_with_none(tree):
    import jax

    return jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: x is None
    )[0]


def _audit_sharding(bundle, compiled, audit: ProgramAudit, out_shape) -> None:
    """Per-leaf actual sharding vs the declared spec: a declared-sharded
    leaf that compiles fully replicated silently costs (N-1)/N of its
    bytes on every device — the regression arxiv 2004.13336's mechanism
    exists to avoid.  ``out_shape`` is the output aval tree the tracing
    pass already produced (make_jaxpr return_shape — no re-trace)."""
    d = bundle.declared
    if d.sharding_in is None:
        return
    ins = compiled.input_shardings[0]
    audit.sharded_in_leaves = _check_shard_tree(
        bundle, "input", d.sharding_in, bundle.avals, ins, audit
    )
    if d.sharding_out is not None:
        audit.sharded_out_leaves = _check_shard_tree(
            bundle, "output", d.sharding_out, out_shape,
            compiled.output_shardings, audit,
        )


def _check_shard_tree(
    bundle, where, expected_tree, aval_tree, sharding_tree, audit
) -> int:
    expected = _flatten_with_none(expected_tree)
    shardings = _flatten_with_none(sharding_tree)
    avals = _flatten_with_none(aval_tree)
    if not (len(expected) == len(shardings) == len(avals)):
        # zip() truncation would silently audit a prefix — the exact
        # silent-replication blind spot this contract exists to close.
        audit.violations.append(
            ProgramViolation(
                bundle.name, "sharding",
                f"{where} trees misaligned: {len(expected)} declared vs "
                f"{len(shardings)} compiled shardings vs {len(avals)} "
                f"avals — auditor assumption broken, treat as drift",
            )
        )
        return 0
    sharded = 0
    for i, (exp_elems, sh, av) in enumerate(
        zip(expected, shardings, avals)
    ):
        if sh is None or exp_elems is None:
            continue  # pruned arg / skipped leaf
        shape = tuple(int(x) for x in av.shape)
        itemsize = hlo_mod.max_operand_itemsize(
            hlo_mod.hlo_dtype_name(av.dtype)
        )
        total = 1
        for x in shape:
            total *= x
        actual_elems = _shard_elems(sh, shape)
        if actual_elems < total:
            sharded += 1
        if actual_elems != exp_elems:
            wasted = (actual_elems - exp_elems) * itemsize
            detail = (
                f"silently replicated — wastes {wasted} B/device"
                if actual_elems == total and exp_elems < total
                else f"shard is {actual_elems} elements, declared {exp_elems}"
            )
            audit.violations.append(
                ProgramViolation(
                    bundle.name, "sharding",
                    f"{where} leaf {i} shape {list(shape)}: declared "
                    f"{exp_elems} elements/device but compiled to "
                    f"{actual_elems} — {detail}",
                )
            )
    return sharded


# --------------------------------------------------------------------------
# baseline: build / validate / compare (stdlib-only code paths)
# --------------------------------------------------------------------------


def build_baseline(audits: List[ProgramAudit]) -> dict:
    import jax

    return {
        "schema": PROGRAM_BASELINE_SCHEMA,
        "generated_by": "scripts/program_audit.py --update-baseline",
        "generated_at": time.time(),
        "generated_at_iso": time.strftime(
            "%Y-%m-%dT%H:%M:%S%z", time.localtime()
        ),
        "jax_version": jax.__version__,
        "devices": len(jax.devices()),
        "axis_size": AXIS_SIZE,
        # Structural fields are compared EXACTLY (a census is not a
        # timing); the tolerance block exists so the gate's policy is
        # recorded next to the data it governs, perf_gate-style.
        "tolerances": {"structural": 0},
        "programs": {a.name: a.baseline_entry() for a in audits},
    }


def validate_program_baseline(obj: object) -> List[str]:
    """Schema errors for a decoded program baseline (empty = valid).
    Stdlib-only: perf_gate --smoke calls this without importing jax."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return ["program baseline is not a JSON object"]
    if obj.get("schema") != PROGRAM_BASELINE_SCHEMA:
        errs.append(
            f"program baseline schema {obj.get('schema')!r} != "
            f"{PROGRAM_BASELINE_SCHEMA}"
        )
    programs = obj.get("programs")
    if not isinstance(programs, dict) or not programs:
        return errs + ["program baseline has no 'programs' table"]
    for name, entry in programs.items():
        if not isinstance(entry, dict) or "jaxpr" not in entry:
            errs.append(f"program {name!r}: entry missing 'jaxpr' block")
            continue
        jx = entry["jaxpr"]
        if not isinstance(jx.get("fences"), int):
            errs.append(f"program {name!r}: jaxpr.fences must be an int")
        if not isinstance(jx.get("census"), list):
            errs.append(f"program {name!r}: jaxpr.census must be a list")
        hl = entry.get("hlo")
        if hl is not None:
            for key in ("fences", "argument_bytes", "aliased_bytes"):
                if not isinstance(hl.get(key), int):
                    errs.append(
                        f"program {name!r}: hlo.{key} must be an int"
                    )
    return errs


def baseline_warnings(
    baseline: dict, max_age_days: float = 90.0,
    now: Optional[float] = None,
) -> List[str]:
    """Staleness/provenance warnings (perf_gate discipline — loud, never
    fatal).  Structural baselines age with the TOOLCHAIN, not the host:
    a jax upgrade can re-schedule collectives, so the stamp records the
    jax version and the warning fires on age or version drift."""
    warnings: List[str] = []
    now = time.time() if now is None else now
    generated_at = baseline.get("generated_at")
    if not isinstance(generated_at, (int, float)) or isinstance(
        generated_at, bool
    ):
        warnings.append(
            "program baseline has no generated_at stamp — regenerate with "
            "scripts/program_audit.py --update-baseline"
        )
    else:
        age_days = (now - float(generated_at)) / 86400.0
        if age_days > max_age_days:
            warnings.append(
                f"program baseline is {age_days:.1f} days old "
                f"(> {max_age_days:g}) — regenerate with --update-baseline"
            )
    recorded = baseline.get("jax_version")
    try:
        # metadata lookup, not `import jax`: perf_gate --smoke calls this
        # on every tier-1 run and must stay jax-import-free.
        from importlib.metadata import version

        current = version("jax")
    except Exception:
        current = None
    if current is not None and recorded not in (None, current):
        warnings.append(
            f"program baseline was generated under jax {recorded}, this "
            f"process runs {current} — XLA may schedule different "
            f"collectives; regenerate with --update-baseline"
        )
    return warnings


def compare_to_baseline(
    audit: ProgramAudit, entry: Optional[dict], fast: bool
) -> List[ProgramViolation]:
    """Drift between one audit and its committed baseline entry.  Exact
    comparison on every structural field; ``--fast`` compares the jaxpr
    block only."""
    out: List[ProgramViolation] = []
    if entry is None:
        out.append(
            ProgramViolation(
                audit.name, "census-drift",
                "program is not in the committed baseline — regenerate "
                "docs/analysis/program_baseline.json (--update-baseline)",
            )
        )
        return out
    jx = entry.get("jaxpr", {})
    for msg in hlo_mod.census_diff(
        jx.get("census", []), audit.jaxpr_census
    ):
        out.append(ProgramViolation(audit.name, "census-drift",
                                    f"jaxpr {msg}"))
    if jx.get("fences") != audit.jaxpr_fences:
        out.append(
            ProgramViolation(
                audit.name, "fence-survival",
                f"jaxpr fence count {audit.jaxpr_fences} != baseline "
                f"{jx.get('fences')}",
            )
        )
    if fast or audit.hlo_census is None:
        return out
    hl = entry.get("hlo")
    if hl is None:
        out.append(
            ProgramViolation(
                audit.name, "census-drift",
                "baseline has no hlo block for this program — regenerate "
                "with --update-baseline (full mode)",
            )
        )
        return out
    for msg in hlo_mod.census_diff(hl.get("census", []), audit.hlo_census):
        out.append(ProgramViolation(audit.name, "census-drift",
                                    f"hlo {msg}"))
    if (
        audit.hlo_fences is not None
        and audit.hlo_fences >= 0
        and isinstance(hl.get("fences"), int)
        and hl["fences"] >= 0
        and audit.hlo_fences != hl["fences"]
    ):
        out.append(
            ProgramViolation(
                audit.name, "fence-survival",
                f"optimized-HLO fence count {audit.hlo_fences} != baseline "
                f"{hl['fences']}",
            )
        )
    for fld, contract in (
        ("argument_bytes", "hbm-bytes"),
        ("output_bytes", "hbm-bytes"),
        ("aliased_bytes", "donation"),
        ("donated_bytes", "donation"),
        ("param_dtypes", "dtype-flow"),
        ("sharded_in_leaves", "sharding"),
        ("sharded_out_leaves", "sharding"),
    ):
        base_v, cur_v = hl.get(fld), getattr(audit, fld)
        if base_v is not None and cur_v is not None and base_v != cur_v:
            out.append(
                ProgramViolation(
                    audit.name, contract,
                    f"{fld} changed: baseline {base_v} -> {cur_v}",
                )
            )
    return out


# --------------------------------------------------------------------------
# injections (the auditor's own regression demonstrations)
# --------------------------------------------------------------------------


def build_injection(which: str) -> ProgramBundle:
    """A deliberately-violating bundle per injection class — the CLI's
    ``--inject`` demonstration that each contract actually fires, exit 1,
    naming program + op + contract (docs/ANALYSIS.md)."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ddlpc_tpu.utils.compat import shard_map

    if which == "extra-collective":
        # An extra live psum smuggled around the real update program: the
        # census gains one all-reduce the closed form does not know.
        bundle = build_program("int8_simulate/update_step")
        mesh = _mesh_for(bundle.arm)
        base = bundle.fn
        extra = shard_map(
            lambda x: lax.psum(x, "data"), mesh=mesh,
            in_specs=(P(),), out_specs=P(), check=False,
        )

        @jax.jit
        def injected(params, opt_state, grads):
            p, o = base(params, opt_state, grads)
            leaves, treedef = jax.tree_util.tree_flatten(p)
            leaves[0] = leaves[0] + 1e-8 * extra(leaves[0])
            return jax.tree_util.tree_unflatten(treedef, leaves), o

        return replace(
            bundle, name="inject/extra-collective", fn=injected,
            declared=replace(bundle.declared, donated_args=()),
        )

    if which == "fp32-widen":
        # The fused wire really IS s8 now, so the widening regression is
        # demonstrated by tracing with the fusion disabled
        # (simulate_wire_dtype -> None: grad_sync falls back to the fp32
        # pmean spelling) while the declaration keeps the honest s8 —
        # exactly what a refactor that quietly reroutes the sync around
        # the fused path would look like.  jax resolves grad_sync's
        # module global at TRACE time, so the patch rides the bundle.
        import contextlib

        @contextlib.contextmanager
        def widened():
            from ddlpc_tpu.parallel import grad_sync

            real = grad_sync.simulate_wire_dtype
            grad_sync.simulate_wire_dtype = lambda axis_size, comp: None
            try:
                yield
            finally:
                grad_sync.simulate_wire_dtype = real

        bundle = build_program("int8_simulate/update_step")
        return replace(bundle, name="inject/fp32-widen", patch=widened)

    if which == "drop-fence":
        # Trace the update program with apply_codec_fenced neutered —
        # the "someone removed the barrier wrapper" regression.  jax
        # resolves the module global at TRACE time, so the patch rides
        # the bundle and audit_program holds it open while tracing.
        import contextlib

        @contextlib.contextmanager
        def unfenced():
            from ddlpc_tpu.parallel import grad_sync

            real = grad_sync.apply_codec_fenced
            grad_sync.apply_codec_fenced = (
                lambda fq, grads, compression, key=None: fq(
                    grads, compression, key=key
                )
            )
            try:
                yield
            finally:
                grad_sync.apply_codec_fenced = real

        bundle = build_program("int8_simulate/update_step")
        return replace(bundle, name="inject/drop-fence", patch=unfenced)

    if which == "replicated-leaf":
        # A leaf declared P('data') compiled fully replicated: audit the
        # REPLICATED update program against the sharded declaration.
        from ddlpc_tpu.parallel import shard_update as zero

        bundle = build_program("none_simulate/update_step")
        arm = bundle.arm
        cfg = _tiny_experiment(arm)
        mesh = _mesh_for(arm)
        _, tx, state = _abstract_state(cfg, mesh)
        spec = zero.opt_partition_specs(
            tx, state.params, "gspmd", "data", n_shards=AXIS_SIZE
        )
        opt_elems = _named_tree(mesh, spec, state.opt_state)
        params_elems = _repl_tree(state.params)
        declared = replace(
            bundle.declared,
            sharding_in=(params_elems, opt_elems, params_elems),
            sharding_out=(params_elems, opt_elems),
        )
        return replace(bundle, name="inject/replicated-leaf",
                       declared=declared)

    raise ValueError(
        f"unknown injection {which!r} (expected one of {INJECTIONS})"
    )
