"""Pallas TPU kernel for the gradient codec: fused quantize→dequantize.

One VMEM pass per leaf: read the gradient block, snap it to the codec
lattice (nearest, or stochastic rounding driven by the TPU core's hardware
PRNG instead of XLA's ALU-heavy threefry), and write the dequantized value —
no intermediate int8/fp16 tensor ever reaches HBM.

Honest placement (docs/PERF.md): traces show XLA already fuses the simulate
codec into ~bandwidth-bound loops (≈0.07 ms per 8M elements nearest,
+0.16 ms for threefry noise), so this kernel is an opt-in backend
(``CompressionConfig.codec_backend='pallas'``), not a default — it exists as
the framework's template for TPU kernels (grid/block layout, SMEM scalars,
hardware PRNG, interpret-mode testing) and to cap the codec's cost on models
whose gradient volume dwarfs the flagship's 7.8M parameters.

Layout: each leaf is raveled and padded to a [rows, 1024] view — the lane
dimension a multiple of 128 so the VPU runs full-width, rows a multiple of 8
sublanes.  The whole-model scale stays an XLA reduction (it crosses leaves);
it enters the kernel as a (1, 1) SMEM scalar.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.ops.quantize import (
    global_absmax,
    levels_for,
    rounding_key,
    safe_divisor,
)

LANES = 1024  # 8 × 128-lane vregs per row
_BLOCK_ROWS = 256  # 256×1024 fp32 = 1 MiB per VMEM block


def default_interpret() -> bool:
    """Run the kernel via the Pallas interpreter off-TPU (CPU test meshes,
    GPU hosts) — Mosaic lowering exists only for real TPU backends."""
    return jax.default_backend() != "tpu"


def _fq_kernel(scale_ref, seed_ref, x_ref, out_ref, *, levels: float, stochastic: bool):
    x = x_ref[...].astype(jnp.float32)
    scaled = x / scale_ref[0, 0] * levels
    if stochastic:
        # Decorrelate blocks: one seed per pallas_call + the grid position.
        pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
        # Unsigned shift (a signed shift would smear the sign bit into the
        # noise, u in (-0.5, 1)), then back to int32 for the float cast —
        # after >> 8 the value fits in 24 bits, so int32 is exact, and
        # Mosaic has no uint32→f32 cast.  u is uniform in [0, 1).
        bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
        u = pltpu.bitcast(bits >> 8, jnp.int32).astype(jnp.float32) * (
            1.0 / (1 << 24)
        )
        snapped = jnp.floor(scaled + u)
    else:
        snapped = jnp.round(scaled)
    snapped = jnp.clip(snapped, -levels, levels)
    out_ref[...] = snapped / levels * scale_ref[0, 0]


def _fq_kernel_hostnoise(scale_ref, x_ref, u_ref, out_ref, *, levels: float):
    """Stochastic variant taking precomputed U[0,1) noise as an input — the
    interpret-mode fallback (the Pallas interpreter has no lowering for the
    TPU PRNG primitives), sharing the snap/clip/dequant logic exactly."""
    x = x_ref[...].astype(jnp.float32)
    scaled = x / scale_ref[0, 0] * levels
    snapped = jnp.clip(jnp.floor(scaled + u_ref[...]), -levels, levels)
    out_ref[...] = snapped / levels * scale_ref[0, 0]


def _fq_leaf(
    x: jax.Array,
    safe_scale: jax.Array,
    levels: float,
    seed: jax.Array,
    interpret: bool,
) -> jax.Array:
    """Fused quantize→dequantize of one leaf (any shape/dtype)."""
    flat = x.ravel()
    n = flat.shape[0]
    rows = -(-n // LANES)
    block_rows = min(_BLOCK_ROWS, -(-rows // 8) * 8)
    # Pad rows to a whole number of blocks so every grid step is full.
    rows_padded = -(-rows // block_rows) * block_rows
    padded = jnp.pad(flat, (0, rows_padded * LANES - n)).reshape(rows_padded, LANES)
    grid = (rows_padded // block_rows,)
    block = lambda: pl.BlockSpec(  # noqa: E731 — two identical specs
        (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    scale_arg = safe_scale.reshape(1, 1).astype(jnp.float32)
    if seed is not None and interpret:
        # Interpreter has no TPU PRNG lowering: draw the noise outside and
        # run the same snap logic (tests exercise exactly the shipped math).
        u = jax.random.uniform(jax.random.key(jnp.abs(seed)), padded.shape)
        out = pl.pallas_call(
            functools.partial(_fq_kernel_hostnoise, levels=levels),
            # fp32 out, whatever the input dtype — matching the XLA decode()
            # (a bf16 output would round the lattice a second time and feed
            # bf16 into the pmean accumulation).
            out_shape=jax.ShapeDtypeStruct(padded.shape, jnp.float32),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                block(),
                block(),
            ],
            out_specs=block(),
            interpret=True,
        )(scale_arg, padded, u)
    else:
        out = pl.pallas_call(
            functools.partial(
                _fq_kernel, levels=levels, stochastic=seed is not None
            ),
            out_shape=jax.ShapeDtypeStruct(padded.shape, jnp.float32),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # scale (1,1)
                pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (1,1)
                block(),
            ],
            out_specs=block(),
            interpret=interpret,
        )(
            scale_arg,
            (jnp.zeros((1, 1), jnp.int32) if seed is None else seed.reshape(1, 1)),
            padded,
        )
    return out.reshape(-1)[:n].reshape(x.shape)


def _encode_kernel(
    scale_ref, seed_ref, x_ref, out_ref, *, levels: float, stochastic: bool
):
    """Encode-to-wire variant of ``_fq_kernel``: same snap/clip against the
    (caller-shared) scale, but the OUTPUT is the narrow lattice itself —
    int8/int16/fp16 for a fused quantized collective — with no dequantize
    multiply (that happens after the collective, on 1/N or summed data)."""
    x = x_ref[...].astype(jnp.float32)
    scaled = x / scale_ref[0, 0] * levels
    if stochastic:
        pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
        bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
        u = pltpu.bitcast(bits >> 8, jnp.int32).astype(jnp.float32) * (
            1.0 / (1 << 24)
        )
        snapped = jnp.floor(scaled + u)
    else:
        snapped = jnp.round(scaled)
    out_ref[...] = jnp.clip(snapped, -levels, levels).astype(out_ref.dtype)


def _encode_kernel_hostnoise(scale_ref, x_ref, u_ref, out_ref, *, levels: float):
    x = x_ref[...].astype(jnp.float32)
    scaled = x / scale_ref[0, 0] * levels
    snapped = jnp.clip(jnp.floor(scaled + u_ref[...]), -levels, levels)
    out_ref[...] = snapped.astype(out_ref.dtype)


def _decode_kernel(inv_ref, q_ref, out_ref):
    """Dequantize wire values: one multiply by the runtime scalar
    ``inv = scale / (levels · axis_size)`` — a single rounding, so it is
    bit-identical to the XLA spelling of the same multiply."""
    out_ref[...] = q_ref[...].astype(jnp.float32) * inv_ref[0, 0]


def _sublane_multiple(dtype) -> int:
    """Minimum second-to-last tile dimension per dtype (TPU tiling): 8
    sublanes for 32-bit, 16 for 16-bit, 32 for 8-bit operands."""
    itemsize = jnp.dtype(dtype).itemsize
    return {1: 32, 2: 16}.get(itemsize, 8)


def _wire_block_layout(x: jax.Array, wire_dtype):
    """Ravel/pad ``x`` to [rows, LANES] with rows a whole number of blocks
    sized for the NARROW dtype's tile multiple (int8 tiles are (32, 128),
    fp16 (16, 128) — the fp32 input trivially satisfies both)."""
    flat = x.ravel()
    n = flat.shape[0]
    mult = _sublane_multiple(wire_dtype)
    rows = -(-n // LANES)
    block_rows = min(_BLOCK_ROWS, -(-rows // mult) * mult)
    rows_padded = -(-rows // block_rows) * block_rows
    padded = jnp.pad(flat, (0, rows_padded * LANES - n)).reshape(
        rows_padded, LANES
    )
    return padded, n, rows_padded // block_rows, block_rows


def _encode_leaf(
    x: jax.Array,
    safe_scale: jax.Array,
    levels: float,
    seed: Optional[jax.Array],
    wire_dtype,
    interpret: bool,
) -> jax.Array:
    padded, n, n_blocks, block_rows = _wire_block_layout(x, wire_dtype)
    block = lambda: pl.BlockSpec(  # noqa: E731 — identical specs
        (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    scale_arg = safe_scale.reshape(1, 1).astype(jnp.float32)
    if seed is not None and interpret:
        u = jax.random.uniform(jax.random.key(jnp.abs(seed)), padded.shape)
        out = pl.pallas_call(
            functools.partial(_encode_kernel_hostnoise, levels=levels),
            out_shape=jax.ShapeDtypeStruct(padded.shape, wire_dtype),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                block(),
                block(),
            ],
            out_specs=block(),
            interpret=True,
        )(scale_arg, padded, u)
    else:
        out = pl.pallas_call(
            functools.partial(
                _encode_kernel, levels=levels, stochastic=seed is not None
            ),
            out_shape=jax.ShapeDtypeStruct(padded.shape, wire_dtype),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # scale (1,1)
                pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (1,1)
                block(),
            ],
            out_specs=block(),
            interpret=interpret,
        )(
            scale_arg,
            (jnp.zeros((1, 1), jnp.int32) if seed is None else seed.reshape(1, 1)),
            padded,
        )
    return out.reshape(-1)[:n].reshape(x.shape)


def encode_to_wire_pallas(
    tree,
    cfg: CompressionConfig,
    safe_scale: jax.Array,
    wire_dtype,
    key: Optional[jax.Array] = None,
    interpret: bool = False,
):
    """Encode a gradient pytree to its WIRE dtype: the lattice values
    themselves (int8/int16/fp16), quantized against a caller-supplied
    scale — the pmax-shared global scale of the fused collective path
    (grad_sync._fenced_wire_encode) — with no dequantize pass.  Nearest
    rounding lands on integer lattice points, so the cast output is
    bit-identical to the XLA ``quantize_with_scale(...).astype(wire)``
    spelling (unlike fake-quantize, there is no dequant multiply to
    FMA-contract differently).  Seeds mirror ``fake_quantize_pallas``."""
    levels = float(levels_for(cfg))
    key = rounding_key(cfg, key)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if key is None:
        seeds = [None] * len(leaves)
    else:
        seeds = list(
            jax.random.randint(
                key, (len(leaves),), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max
            )
        )
    out = [
        _encode_leaf(l, safe_scale, levels, s, wire_dtype, interpret)
        for l, s in zip(leaves, seeds)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def decode_from_wire_pallas(
    tree, inv_step: jax.Array, interpret: bool = False
):
    """Dequantize summed wire values: ``q · inv_step`` per element, where
    ``inv_step = scale / (levels · axis_size)`` folds the mean division
    into the one runtime-scalar multiply (quantize.decode's convention).
    Deliberately NOT fenced — the fused path leaves the decode free to
    fuse into the collective's consumer (grad_sync._wire_decode)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    inv_arg = inv_step.reshape(1, 1).astype(jnp.float32)
    out = []
    for q in leaves:
        padded, n, n_blocks, block_rows = _wire_block_layout(q, q.dtype)
        block = lambda: pl.BlockSpec(  # noqa: E731 — identical specs
            (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        dec = pl.pallas_call(
            _decode_kernel,
            out_shape=jax.ShapeDtypeStruct(padded.shape, jnp.float32),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), block()],
            out_specs=block(),
            interpret=interpret,
        )(inv_arg, padded)
        out.append(dec.reshape(-1)[:n].reshape(q.shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def fake_quantize_pallas(
    tree,
    cfg: CompressionConfig,
    key: Optional[jax.Array] = None,
    interpret: bool = False,
):
    """Drop-in equivalent of ``ops.quantize.fake_quantize`` running the
    per-element work as one fused Pallas pass per leaf.

    Nearest rounding is bit-identical to the XLA path.  Stochastic rounding
    draws from the TPU hardware PRNG (per-leaf seed derived from ``key``),
    so it matches the XLA path in distribution — unbiased, same error bound
    — but not bit-for-bit.  ``interpret=True`` runs the kernel in the Pallas
    interpreter (any backend; used by the CPU test suite).
    """
    if cfg.mode == "none":
        return tree
    key = rounding_key(cfg, key)
    levels = float(levels_for(cfg))
    scale = global_absmax(tree)
    safe = safe_divisor(scale)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if key is None:
        seeds = [None] * len(leaves)
    else:
        # One int32 seed per leaf from the caller's key, so leaves draw
        # independent noise (mirrors _leaf_keys in the XLA path).
        seeds = list(
            jax.random.randint(
                key, (len(leaves),), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max
            )
        )
    out = [
        _fq_leaf(l, safe, levels, s, interpret) for l, s in zip(leaves, seeds)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)
