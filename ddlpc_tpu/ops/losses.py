"""Segmentation losses.

The reference uses ``nn.CrossEntropyLoss()`` over NCHW logits
(кластер.py:703,755).  Here: mean softmax cross-entropy over NHWC logits with
integer labels, fp32 accumulation, optional ignore_index and label smoothing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _per_pixel_nll(
    logits: jax.Array, labels: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    # One-hot select instead of take_along_axis: a per-pixel gather lowers to
    # a serialized custom kernel on TPU (profiled at ~128 ms per micro-batch
    # for [32,512,512,6] — half the train step), while compare+select+reduce
    # fuses into the surrounding elementwise work.  logsumexp instead of
    # log_softmax avoids materializing an fp32 [..., C] log-prob tensor.
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    lse = jax.nn.logsumexp(logits, axis=-1)
    labels_clipped = jnp.clip(labels, 0, num_classes - 1).astype(jnp.int32)
    onehot = labels_clipped[..., None] == jnp.arange(num_classes, dtype=jnp.int32)
    picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)
    nll = lse - picked
    if label_smoothing > 0.0:
        smooth = lse - logits.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll


def nll_correct_valid(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One fused pass over the logits: per-pixel (NLL, tie-corrected
    correctness, validity) — the train step's loss AND accuracy inputs.

    Motivation is measured, not stylistic (docs/head_bench/
    trace_plain_grouped.json): computing loss and accuracy separately via
    an up-front ``logits.astype(float32)`` materialized a full fp32 copy
    of the largest tensor in the step plus four ~11.5 ms layout-transposed
    intermediates — ~90 ms of a 273 ms step.  Here the bf16 logits are
    read once; every fp32 cast happens inside the elementwise chain (in
    registers), the row max is shared between logsumexp and the
    correctness compare, and nothing class-shaped is materialized in fp32.

    Numerics: identical to the separate paths up to fp reassociation —
    ``logsumexp(f32(l)) == f32(m) + log Σ exp(f32(l) − f32(m))`` with m
    the row max, and the tie semantics are unchanged (bf16 values compare
    equal iff their f32 casts do).  Guarded by
    tests/test_metrics.py::test_fused_nll_matches_separate_paths.
    """
    num_classes = logits.shape[-1]
    labels_clipped = jnp.clip(labels, 0, num_classes - 1).astype(jnp.int32)
    onehot = labels_clipped[..., None] == jnp.arange(num_classes, dtype=jnp.int32)
    m = logits.max(axis=-1)
    zf = logits.astype(jnp.float32) - m.astype(jnp.float32)[..., None]
    lse = m.astype(jnp.float32) + jnp.log(jnp.sum(jnp.exp(zf), axis=-1))
    picked = jnp.sum(jnp.where(onehot, zf, 0.0), axis=-1)  # = logit − max
    nll = lse - m.astype(jnp.float32) - picked
    # Tie-corrected correctness (ops/metrics.py:pixel_accuracy semantics):
    # a pixel counts 1/#tied iff its label's logit equals the row max.
    is_max = (logits == m[..., None])
    ties = jnp.sum(is_max.astype(jnp.float32), axis=-1)
    label_is_max = jnp.sum(
        jnp.where(onehot & is_max, 1.0, 0.0), axis=-1
    )
    correct = label_is_max / jnp.maximum(ties, 1.0)
    if ignore_index is None:
        valid = jnp.ones(nll.shape, jnp.float32)
    else:
        valid = (labels != ignore_index).astype(jnp.float32)
    return nll, correct, valid


def softmax_cross_entropy_sum(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
    label_smoothing: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """(summed NLL, valid-pixel count) — for callers that combine shards or
    batches: sum both then divide once, giving an exactly pixel-weighted mean
    even when pieces hold different numbers of valid (non-padded) pixels."""
    nll = _per_pixel_nll(logits, labels, label_smoothing)
    if ignore_index is None:
        valid = jnp.ones_like(nll)
    else:
        valid = (labels != ignore_index).astype(jnp.float32)
    return (nll * valid).sum(), valid.sum()


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Mean pixel cross-entropy.

    logits: [..., C] float; labels: [...] int.  Matches torch
    CrossEntropyLoss (mean reduction) semantics on valid pixels.
    """
    nll_sum, count = softmax_cross_entropy_sum(
        logits, labels, ignore_index, label_smoothing
    )
    return nll_sum / jnp.maximum(count, 1.0)
