"""Segmentation losses.

The reference uses ``nn.CrossEntropyLoss()`` over NCHW logits
(кластер.py:703,755).  Here: mean softmax cross-entropy over NHWC logits with
integer labels, fp32 accumulation, optional ignore_index and label smoothing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Mean pixel cross-entropy.

    logits: [..., C] float; labels: [...] int.  Matches torch
    CrossEntropyLoss (mean reduction) semantics on valid pixels.
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    labels_clipped = jnp.clip(labels, 0, num_classes - 1)
    nll = -jnp.take_along_axis(
        log_probs, labels_clipped[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    if label_smoothing > 0.0:
        smooth = -log_probs.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    if ignore_index is None:
        return nll.mean()
    valid = (labels != ignore_index).astype(jnp.float32)
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def softmax_cross_entropy_sum(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
) -> tuple[jax.Array, jax.Array]:
    """(summed NLL, valid-pixel count) — for callers that combine shards:
    psum both then divide, giving an exactly pixel-weighted global mean even
    when shards hold different numbers of valid (non-padded) pixels."""
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    labels_clipped = jnp.clip(labels, 0, num_classes - 1)
    nll = -jnp.take_along_axis(
        log_probs, labels_clipped[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    if ignore_index is None:
        valid = jnp.ones_like(nll)
    else:
        valid = (labels != ignore_index).astype(jnp.float32)
    return (nll * valid).sum(), valid.sum()
