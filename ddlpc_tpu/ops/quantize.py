"""Lossy gradient quantization codec — the reference's research contribution.

The reference compresses gradients for its bandwidth-constrained LAN with a
*global* (whole-model) max-abs scale, then either:
- int8: ``round(g / max * 10)`` stored as int8 — 21 levels (кластер.py:474,478);
- float16: ``round(g / max * 100)`` stored as fp16 — 201 levels (кластер.py:487,491);
payload ``[float(max), per-layer bytes]`` (кластер.py:483,496), dequantized as
``q / levels * max`` (кластер.py:533,543).

This module reimplements that scheme as pure jittable pytree transforms,
fixing the reference's two defects (SURVEY §2.8c/d): the ``max==0`` NameError
crash (кластер.py:345-396) and the broken float32 path that zeroes gradients
(кластер.py:315,432,545).  The averaging itself lives in
``parallel/grad_sync.py`` and is an exact mean over replicas, not the
reference's "crooked averaging (fix!)" (кластер.py:268).

On TPU this codec is meaningful across DCN (multi-host links) and as an
HBM-traffic reducer; within an ICI slice plain fp32/bf16 psum usually wins.
The fake-quantize form (encode→decode locally) is used inside the jitted
train step to make training *semantics* identical whether or not the wire is
actually compressed.

Serving reuses the same lattice (``quantize_with_scale`` + ``safe_divisor``)
for weight quantization with PER-LEAF scales at levels=127 — static tensors
quantized once per restore instead of per step (``serve/quantized.py``;
docs/QUANTIZATION.md "Serving-side weight quantization").
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ddlpc_tpu.config import CompressionConfig

PyTree = Any


class Encoded(NamedTuple):
    """Quantized pytree payload: one global fp32 scale + discretized leaves."""

    scale: jax.Array  # scalar fp32, the whole-model max-abs (кластер.py:483)
    tree: PyTree  # int8 or fp16 leaves, same structure as the input


def levels_for(cfg: CompressionConfig) -> int:
    """Level count for a quantizing mode; raises on unknown modes so every
    codec consumer (simulate and ring transport alike) rejects them."""
    if cfg.mode == "int8":
        if not 0 < cfg.int8_levels <= 127:
            # ±levels must survive the int8 cast: beyond 127 the cast WRAPS
            # (200 → -56), silently sign-flipping gradients.
            raise ValueError(
                f"int8_levels must be in [1, 127], got {cfg.int8_levels}"
            )
        return cfg.int8_levels
    if cfg.mode == "float16":
        if cfg.fp16_levels <= 0:
            raise ValueError(f"fp16_levels must be positive, got {cfg.fp16_levels}")
        return cfg.fp16_levels
    raise ValueError(f"unknown compression mode {cfg.mode!r}")


def quantize_with_scale(
    x: jax.Array,
    safe_scale: jax.Array,
    levels: float,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """x/scale·levels snapped to the integer lattice, clipped to ±levels,
    as fp32 lattice values.

    The one quantization formula, shared by the simulate codec (encode) and
    the ring transport (compressed_allreduce.py) so their loss points cannot
    drift.  ``safe_scale`` must already be zero-guarded (see encode).

    ``key=None`` → round-to-nearest (the reference's round(), кластер.py:474).
    With a key → stochastic rounding, floor(v + U[0,1)): unbiased
    (E[result] == v) at the cost of one extra half-step of worst-case error.
    """
    return snap_to_lattice(x.astype(jnp.float32) / safe_scale * levels, levels, key)


def snap_to_lattice(
    scaled: jax.Array,
    levels: float,
    key: Optional[jax.Array] = None,
    noise: Optional[jax.Array] = None,
) -> jax.Array:
    """Snap values already in lattice units to integers, clipped to ±levels
    (nearest without a key; stochastic floor(v + U[0,1)) with one).

    ``noise`` supplies a precomputed U[0,1) field instead of drawing one
    from ``key`` — the sharded-update path (grad_sync.sync_gradients_scatter)
    draws the noise at the FULL leaf's shape and slices each replica's
    chunk, so a shard's rounding decisions are bit-identical to the
    corresponding elements of the replicated path's draw.  Mutually
    exclusive with ``key``."""
    if noise is not None:
        if key is not None:
            raise ValueError("pass either key or noise, not both")
        snapped = jnp.floor(scaled + noise)
    elif key is None:
        snapped = jnp.round(scaled)
    else:
        snapped = jnp.floor(scaled + jax.random.uniform(key, scaled.shape))
    return jnp.clip(snapped, -levels, levels)


def safe_divisor(scale: jax.Array) -> jax.Array:
    """Zero-guard for the reference's max==0 crash (кластер.py:345-396): a
    zero scale makes g/scale NaN; divide by 1 instead (the quantized values
    are all 0 anyway when scale == 0)."""
    return jnp.where(scale > 0, scale, 1.0)


def global_absmax(tree: PyTree) -> jax.Array:
    """Whole-model max |g| — the reference's single global scale
    (кластер.py:463-471 computes max over every layer)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.max(
        jnp.stack([jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves])
    )


def _leaf_keys(tree: PyTree, key: Optional[jax.Array]) -> PyTree:
    """One independent PRNG subkey per leaf (None tree when key is None)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if key is None:
        return jax.tree_util.tree_unflatten(treedef, [None] * len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, list(jax.random.split(key, len(leaves)))
    )


def rounding_key(cfg: CompressionConfig, key: Optional[jax.Array]):
    """Resolve the key to pass into quantization for this config: None for
    nearest rounding; the caller's key for stochastic (raising if absent, so
    a stochastic config can never silently fall back to biased rounding)."""
    if cfg.rounding == "nearest":
        return None
    if cfg.rounding == "stochastic":
        if key is None:
            raise ValueError(
                "rounding='stochastic' needs a PRNG key (the train step "
                "derives one from the step counter)"
            )
        return key
    raise ValueError(f"unknown rounding {cfg.rounding!r}")


def encode(
    tree: PyTree, cfg: CompressionConfig, key: Optional[jax.Array] = None
) -> Encoded:
    """Quantize a gradient pytree.  mode='none' stores fp32 unchanged."""
    scale = global_absmax(tree)
    safe = safe_divisor(scale)
    if cfg.mode == "none":
        return Encoded(scale, jax.tree.map(lambda g: g.astype(jnp.float32), tree))
    key = rounding_key(cfg, key)
    levels = float(levels_for(cfg))
    out_dtype = jnp.int8 if cfg.mode == "int8" else jnp.float16
    q = jax.tree.map(
        lambda g, k: quantize_with_scale(g, safe, levels, key=k).astype(out_dtype),
        tree,
        _leaf_keys(tree, key),
    )
    return Encoded(scale, q)


def decode(enc: Encoded, cfg: CompressionConfig) -> PyTree:
    """Dequantize: q · (scale / levels) — the reference's q / levels · scale
    (кластер.py:533,543) algebraically, restructured as ONE elementwise
    multiply by a runtime scalar.  The direct form divides by the
    compile-time CONSTANT ``levels``, which LLVM may rewrite to a
    reciprocal multiply in one compilation and not another (fast-math is
    shape/context dependent) — observed as 1-ulp drift between the
    replicated and ZeRO-sharded train steps dequantizing identical lattice
    points.  A runtime-scalar multiply has exactly one rounding and no
    rewritable constant divisor, so every program dequantizes
    bit-identically (docs/SHARDING.md)."""
    if cfg.mode == "none":
        return enc.tree
    step = enc.scale / float(levels_for(cfg))
    return jax.tree.map(lambda q: q.astype(jnp.float32) * step, enc.tree)


def fake_quantize(
    tree: PyTree, cfg: CompressionConfig, key: Optional[jax.Array] = None
) -> PyTree:
    """encode→decode round trip: injects exactly the codec's information loss
    without materializing wire bytes.  Identity when mode='none'."""
    if cfg.mode == "none":
        return tree
    return decode(encode(tree, cfg, key=key), cfg)


def quantization_error_bound(cfg: CompressionConfig) -> float:
    """Max per-element |decode(encode(g)) - g| as a fraction of the global
    absmax: half a quantization step for nearest rounding, a full step for
    stochastic (which trades that worst case for zero bias)."""
    if cfg.mode == "none":
        return 0.0
    step = 1.0 / levels_for(cfg)
    return step if cfg.rounding == "stochastic" else 0.5 * step
