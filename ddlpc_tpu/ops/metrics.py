"""Segmentation metrics: pixel accuracy, confusion matrix, mIoU.

The reference computes only train-set pixel accuracy
(mean(argmax(outputs)==Y), кластер.py:775) and never mIoU; mIoU is the
BASELINE.json north-star metric, so it is first-class here.  All functions are
jit-friendly (static shapes, no data-dependent control flow); the confusion
matrix is accumulated streaming across batches and reduced once at the end.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def pixel_accuracy(
    logits: jax.Array, labels: jax.Array, ignore_index: Optional[int] = None
) -> jax.Array:
    """Fraction of pixels where the label's logit is the row max
    (кластер.py:775 computes mean(argmax(outputs)==Y)).

    Deliberately argmax-free: an explicit argmax over [B,H,W,C] lowers to an
    iota + s32 reduction with full-size integer intermediates (profiled at
    ~15% of the Cityscapes train step); comparing the label's logit against
    the row max fuses into the surrounding elementwise work.  Exact-tie
    pixels — negligible for fp32 logits but common early in training with
    bfloat16 heads (ModelConfig.head_dtype), where near-uniform logits round
    onto identical values — count as 1/#tied rather than 1, i.e. the
    probability a uniform tie-break picks the label, so bf16 ties cannot
    inflate the metric.  The eval/mIoU path keeps true argmax
    (confusion_from_logits)."""
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    labels_clipped = jnp.clip(labels, 0, num_classes - 1).astype(jnp.int32)
    onehot = labels_clipped[..., None] == jnp.arange(num_classes, dtype=jnp.int32)
    picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)
    row_max = logits.max(axis=-1)
    ties = jnp.sum((logits == row_max[..., None]).astype(jnp.float32), axis=-1)
    correct = (picked >= row_max).astype(jnp.float32) / jnp.maximum(ties, 1.0)
    if ignore_index is None:
        return correct.mean()
    valid = (labels != ignore_index).astype(jnp.float32)
    return (correct * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def confusion_matrix(
    preds: jax.Array,
    labels: jax.Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """[C, C] float32 counts, rows = true class, cols = predicted class.

    Implemented with a flat scatter-add so XLA lowers it to one
    segment-sum — no Python loops over classes.
    """
    preds = preds.reshape(-1).astype(jnp.int32)
    labels = labels.reshape(-1).astype(jnp.int32)
    valid = (labels >= 0) & (labels < num_classes)
    if ignore_index is not None:
        valid &= labels != ignore_index
    idx = jnp.where(valid, labels * num_classes + jnp.clip(preds, 0, num_classes - 1), 0)
    weights = valid.astype(jnp.float32)
    flat = jnp.zeros(num_classes * num_classes, jnp.float32).at[idx].add(weights)
    return flat.reshape(num_classes, num_classes)


def confusion_from_logits(
    logits: jax.Array,
    labels: jax.Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    return confusion_matrix(
        jnp.argmax(logits, axis=-1), labels, num_classes, ignore_index
    )


def iou_per_class(cm: jax.Array) -> jax.Array:
    """Per-class IoU from a confusion matrix; NaN-free (absent classes → 0)."""
    tp = jnp.diag(cm)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    denom = tp + fp + fn
    return jnp.where(denom > 0, tp / jnp.maximum(denom, 1.0), 0.0)


def mean_iou(cm: jax.Array, present_only: bool = True) -> jax.Array:
    """mIoU.  present_only averages over classes that occur in labels or preds."""
    ious = iou_per_class(cm)
    if not present_only:
        return ious.mean()
    tp = jnp.diag(cm)
    present = (cm.sum(axis=0) + cm.sum(axis=1)) > 0
    return jnp.where(
        present.sum() > 0, (ious * present).sum() / jnp.maximum(present.sum(), 1), 0.0
    )


def accuracy_from_confusion(cm: jax.Array) -> jax.Array:
    return jnp.diag(cm).sum() / jnp.maximum(cm.sum(), 1.0)
