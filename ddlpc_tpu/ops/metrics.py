"""Segmentation metrics: pixel accuracy, confusion matrix, mIoU.

The reference computes only train-set pixel accuracy
(mean(argmax(outputs)==Y), кластер.py:775) and never mIoU; mIoU is the
BASELINE.json north-star metric, so it is first-class here.  All functions are
jit-friendly (static shapes, no data-dependent control flow); the confusion
matrix is accumulated streaming across batches and reduced once at the end.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def pixel_accuracy(
    logits: jax.Array, labels: jax.Array, ignore_index: Optional[int] = None
) -> jax.Array:
    """Fraction of pixels where argmax(logits) == label (кластер.py:775)."""
    preds = jnp.argmax(logits, axis=-1)
    correct = (preds == labels).astype(jnp.float32)
    if ignore_index is None:
        return correct.mean()
    valid = (labels != ignore_index).astype(jnp.float32)
    return (correct * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def confusion_matrix(
    preds: jax.Array,
    labels: jax.Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """[C, C] float32 counts, rows = true class, cols = predicted class.

    Implemented with a flat scatter-add so XLA lowers it to one
    segment-sum — no Python loops over classes.
    """
    preds = preds.reshape(-1).astype(jnp.int32)
    labels = labels.reshape(-1).astype(jnp.int32)
    valid = (labels >= 0) & (labels < num_classes)
    if ignore_index is not None:
        valid &= labels != ignore_index
    idx = jnp.where(valid, labels * num_classes + jnp.clip(preds, 0, num_classes - 1), 0)
    weights = valid.astype(jnp.float32)
    flat = jnp.zeros(num_classes * num_classes, jnp.float32).at[idx].add(weights)
    return flat.reshape(num_classes, num_classes)


def confusion_from_logits(
    logits: jax.Array,
    labels: jax.Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    return confusion_matrix(
        jnp.argmax(logits, axis=-1), labels, num_classes, ignore_index
    )


def iou_per_class(cm: jax.Array) -> jax.Array:
    """Per-class IoU from a confusion matrix; NaN-free (absent classes → 0)."""
    tp = jnp.diag(cm)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    denom = tp + fp + fn
    return jnp.where(denom > 0, tp / jnp.maximum(denom, 1.0), 0.0)


def mean_iou(cm: jax.Array, present_only: bool = True) -> jax.Array:
    """mIoU.  present_only averages over classes that occur in labels or preds."""
    ious = iou_per_class(cm)
    if not present_only:
        return ious.mean()
    tp = jnp.diag(cm)
    present = (cm.sum(axis=0) + cm.sum(axis=1)) > 0
    return jnp.where(
        present.sum() > 0, (ious * present).sum() / jnp.maximum(present.sum(), 1), 0.0
    )


def accuracy_from_confusion(cm: jax.Array) -> jax.Array:
    return jnp.diag(cm).sum() / jnp.maximum(cm.sum(), 1.0)
