"""The exit-status + breadcrumb protocol between trainer and supervisor.

A supervised training process communicates its fate through two channels
that survive the process itself:

- **exit status** — coarse, always present:

  ========== =============== ==========================================
  status     name            meaning
  ========== =============== ==========================================
  0          EXIT_CLEAN      run completed all epochs
  42         EXIT_STALL      watchdog abort: heartbeat went quiet
                             (train/watchdog.py — pre-existing contract)
  43         EXIT_PREEMPTED  preemption-graceful shutdown: the in-flight
                             step finished, an emergency checkpoint was
                             written, telemetry drained
  < 0 / 137  (signal)        killed from outside (SIGKILL ⇒ possible OOM)
  other      (crash)         unhandled exception, import error, ...
  ========== =============== ==========================================

- **breadcrumb** — ``<workdir>/breadcrumb.json``, a tiny atomically-replaced
  JSON file the trainer rewrites at phase transitions (running → per-
  checkpoint progress → preempted/stalled/done).  The supervisor reads it
  after every exit to refine the coarse status: a ``-9`` with a breadcrumb
  still in phase ``running`` reads as an external kill/OOM, a ``43`` whose
  breadcrumb says ``preempt_timeout`` means the grace window expired before
  the emergency checkpoint landed (resume falls back to the previous one).

Deliberately stdlib-only: the supervisor imports this without paying the
jax import, so the parent process that must outlive crashes stays light.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
from typing import Optional

EXIT_CLEAN = 0
EXIT_STALL = 42  # StallWatchdog's distinctive abort status (pre-existing)
EXIT_PREEMPTED = 43  # graceful preemption shutdown (trainer SIGTERM path)

BREADCRUMB = "breadcrumb.json"

# Mirror of train/checkpoint.py's _CKPT_RE, duplicated so the supervisor
# can measure checkpoint progress without importing jax/flax.  Quarantined
# ``*.bad`` blobs deliberately do not match — they are not progress.
_CKPT_RE = re.compile(r"^ckpt_(\d+)\.(?:msgpack\.z|dwc)$")


def write_breadcrumb(workdir: str, phase: str, **fields) -> None:
    """Atomically rewrite the breadcrumb.  Best-effort: diagnostics must
    never take down the run they describe — every failure is swallowed."""
    try:
        os.makedirs(workdir, exist_ok=True)
        crumb = {
            "schema": 1,
            "phase": phase,
            "pid": os.getpid(),
            "time": time.time(),
        }
        crumb.update(fields)
        fd, tmp = tempfile.mkstemp(dir=workdir, suffix=".crumb.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(crumb, f)
        # Rename-atomic, deliberately NOT fsynced: breadcrumbs are written
        # per epoch and fsync costs ~50ms on containerized filesystems —
        # a reader sees a whole crumb or the previous one, and losing the
        # newest crumb to power loss only costs one supervisor-side
        # progress classification (the checkpoint path owns durability).
        os.replace(tmp, os.path.join(workdir, BREADCRUMB))
    except Exception:
        pass


def read_breadcrumb(workdir: str) -> Optional[dict]:
    """The last breadcrumb, or None (missing, torn, or unreadable)."""
    try:
        with open(os.path.join(workdir, BREADCRUMB)) as f:
            return json.load(f)
    except Exception:
        return None


def latest_checkpoint_step(ckpt_dir: str) -> Optional[int]:
    """Newest live checkpoint step in ``ckpt_dir`` without importing jax —
    the supervisor's progress signal (crash loops are 'N failures without
    THIS advancing')."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    steps = [int(m.group(1)) for m in map(_CKPT_RE.match, names) if m]
    return max(steps) if steps else None
