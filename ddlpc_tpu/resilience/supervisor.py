"""Process supervisor: launch, watch, classify, back off, relaunch, resume.

The recovery story the watchdog docstring promises (train/watchdog.py:
detect → die → restart → resume) needs a process that OUTLIVES the
training process.  Until this PR that process existed only inside
``tests/test_recovery_loop.py``; this is the shipped version the test now
exercises, the soak harness drives, and a cluster entrypoint can wrap:

    python -m ddlpc_tpu.resilience.supervisor --workdir runs/x -- \\
        python -m ddlpc_tpu.train --config configs/x.json --workdir runs/x

Behavior:

- **Exit-cause classification** via the structured exit-status/breadcrumb
  protocol (resilience/protocol.py): clean (0) ends supervision;
  watchdog stall (42), graceful preemption (43), crashes, and external
  kills (SIGKILL ⇒ possible OOM) each restart with their own accounting,
  emitted as ``ddlpc_restarts_total{cause}`` through the obs registry and
  as flat schema-stamped records in ``<workdir>/resilience.jsonl``.
- **Exponential backoff + full jitter** between restarts that made no
  checkpoint progress (base·2^n capped, uniformly jittered — the fleet-
  thundering-herd standard); progressing restarts and graceful
  preemptions relaunch immediately.
- **Crash-loop detection**: ``crash_loop_limit`` consecutive failures
  without the newest checkpoint step advancing → give up LOUDLY (a
  critical record + stderr banner + nonzero status) instead of burning a
  restart budget on a deterministic crash.
- **Signal forwarding**: SIGTERM/SIGINT to the supervisor forward to the
  child (which runs its graceful-preemption path) and end supervision
  after the child exits — the whole tree preempts as one unit.

Stdlib + obs-registry only: the supervisor must stay importable and alive
when the training process cannot even reach its first jax import.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ddlpc_tpu.obs.registry import MetricsRegistry
from ddlpc_tpu.obs.schema import SCHEMA_VERSION
from ddlpc_tpu.resilience.protocol import (
    EXIT_CLEAN,
    EXIT_PREEMPTED,
    EXIT_STALL,
    latest_checkpoint_step,
    read_breadcrumb,
)


def classify_exit(returncode: int, breadcrumb: Optional[dict] = None) -> str:
    """Coarse exit status + breadcrumb → one cause label.

    Causes: ``clean`` | ``stall`` | ``preempted`` | ``oom_kill`` |
    ``signal`` | ``crash``.  The breadcrumb refines ambiguity the status
    cannot carry — e.g. a process that died of SIGKILL while its crumb
    still says ``running`` is an external kill/OOM, not a code path.
    """
    phase = (breadcrumb or {}).get("phase")
    if returncode == EXIT_CLEAN:
        return "clean"
    if returncode == EXIT_STALL or phase == "stalled":
        return "stall"
    if returncode == EXIT_PREEMPTED or phase in ("preempted", "preempt_timeout"):
        return "preempted"
    if returncode in (-signal.SIGKILL, 128 + signal.SIGKILL):
        # SIGKILL is what both the kernel OOM killer and an impatient
        # scheduler send; without a crumb saying otherwise, treat as OOM-
        # class (restartable, but worth distinct accounting).
        return "oom_kill"
    if returncode < 0:
        return "signal"
    return "crash"


class RestartPolicy:
    """The backoff / crash-loop / give-up state machine, extracted so the
    training :class:`Supervisor` and the serve fleet's ``ReplicaSupervisor``
    (serve/fleet.py) restart things by ONE set of rules:

    - full-jitter exponential backoff between restarts that made no
      progress (``uniform(0, min(cap, base·2^(streak-1)))``);
    - ``crash_loop_limit`` consecutive no-progress exits → give up;
    - ``max_restarts`` total restarts → give up.

    "Progress" is the caller's notion (the training supervisor: the newest
    checkpoint step advanced or a graceful preemption completed; the fleet:
    the replica became ready and served traffic since launch).  The policy
    only tracks the streak.
    """

    def __init__(
        self,
        max_restarts: int = 100,
        crash_loop_limit: int = 3,
        backoff_base_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        rng: Optional[random.Random] = None,
    ):
        if crash_loop_limit < 1:
            raise ValueError(
                f"crash_loop_limit must be >= 1, got {crash_loop_limit}"
            )
        self.max_restarts = int(max_restarts)
        self.crash_loop_limit = int(crash_loop_limit)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.rng = rng if rng is not None else random.Random()
        self.fail_streak = 0
        self.attempts = 0  # exits recorded (== restarts granted so far + 1)

    def backoff_s(self, fail_streak: int) -> float:
        """Full-jitter exponential backoff for the Nth consecutive
        no-progress failure (streak >= 1): uniform(0, min(cap, base·2^(N-1)))."""
        if fail_streak <= 0:
            return 0.0
        ceiling = min(
            self.backoff_cap_s,
            self.backoff_base_s * (2.0 ** (fail_streak - 1)),
        )
        return self.rng.uniform(0.0, ceiling)

    def record_exit(self, progressed: bool) -> str:
        """Account one child exit; returns the decision:
        ``"restart"`` | ``"give_up_crash_loop"`` | ``"give_up_budget"``."""
        self.attempts += 1
        if progressed:
            self.fail_streak = 0
        else:
            self.fail_streak += 1
        if self.fail_streak >= self.crash_loop_limit:
            return "give_up_crash_loop"
        if self.attempts > self.max_restarts:
            return "give_up_budget"
        return "restart"

    def delay_s(self) -> float:
        """The backoff to sleep before the restart just granted."""
        return self.backoff_s(self.fail_streak)


@dataclass
class SupervisorResult:
    """What a supervision run amounted to."""

    final_status: int
    attempts: int
    restarts_by_cause: Dict[str, int] = field(default_factory=dict)
    gave_up: bool = False
    reason: str = ""

    @property
    def ok(self) -> bool:
        return self.final_status == EXIT_CLEAN and not self.gave_up


class Supervisor:
    """Relaunch ``cmd`` until it exits clean, gives up, or is told to stop.

    ``env_fn(attempt) -> dict | None`` lets a caller vary the child's
    environment per attempt — how the chaos soak injects a different fault
    into each relaunch (resilience/chaos.py counts steps per process, so a
    schedule that killed attempt 0 at step K would kill every restart at
    step K too unless rewritten).  ``sleep``/``rng``/``popen`` are
    injectable so the backoff/crash-loop logic unit-tests with a fake
    clock and no real processes.
    """

    def __init__(
        self,
        cmd: Sequence[str],
        workdir: str,
        ckpt_dir: Optional[str] = None,
        max_restarts: int = 100,
        crash_loop_limit: int = 3,
        backoff_base_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        env_fn: Optional[Callable[[int], Optional[dict]]] = None,
        registry: Optional[MetricsRegistry] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        popen: Callable[..., "subprocess.Popen"] = subprocess.Popen,
        echo: bool = True,
    ):
        self.cmd = list(cmd)
        self.workdir = workdir
        self.ckpt_dir = ckpt_dir or os.path.join(workdir, "checkpoints")
        self.policy = RestartPolicy(
            max_restarts=max_restarts,
            crash_loop_limit=crash_loop_limit,
            backoff_base_s=backoff_base_s,
            backoff_cap_s=backoff_cap_s,
            rng=rng,
        )
        self.max_restarts = self.policy.max_restarts
        self.crash_loop_limit = self.policy.crash_loop_limit
        self.env_fn = env_fn
        self.registry = registry if registry is not None else MetricsRegistry()
        self._restarts = self.registry.counter(
            "ddlpc_restarts_total",
            "Supervised training restarts, by classified exit cause.",
            labelnames=("cause",),
        )
        self._sleep = sleep
        self._popen = popen
        self.echo = echo
        self._stop = threading.Event()
        self._child: Optional[subprocess.Popen] = None
        self._jsonl_path = os.path.join(workdir, "resilience.jsonl")

    # -- plumbing -----------------------------------------------------------

    def _say(self, msg: str) -> None:
        if self.echo:
            print(f"[supervisor] {msg}", file=sys.stderr, flush=True)

    def _log(self, record: dict) -> None:
        """Append one flat schema-stamped record to resilience.jsonl (the
        stream scripts/check_metrics_schema.py lints and obs_tail.py
        tails).  Best-effort — supervision must survive a full disk."""
        record = dict(record)
        record.setdefault("schema", SCHEMA_VERSION)
        record.setdefault("time", time.time())
        try:
            os.makedirs(self.workdir, exist_ok=True)
            with open(self._jsonl_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass

    def request_stop(self, sig: int = signal.SIGTERM) -> None:
        """Forward ``sig`` to the child and end supervision after it exits
        (no further restarts).  Safe from signal handlers and threads."""
        self._stop.set()
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    def backoff_s(self, fail_streak: int) -> float:
        """Full-jitter backoff for the Nth consecutive no-progress failure
        (delegates to :class:`RestartPolicy` — one impl for both
        supervisors)."""
        return self.policy.backoff_s(fail_streak)

    # -- the loop -----------------------------------------------------------

    def run(self) -> SupervisorResult:
        attempt = 0
        restarts: Dict[str, int] = {}
        installed = []
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev = signal.signal(
                        sig, lambda s, f: self.request_stop(signal.SIGTERM)
                    )
                    installed.append((sig, prev))
                except (ValueError, OSError):
                    pass
        try:
            while True:
                step_before = latest_checkpoint_step(self.ckpt_dir)
                env = self.env_fn(attempt) if self.env_fn is not None else None
                self._say(
                    f"attempt {attempt}: launching {' '.join(self.cmd[:3])}... "
                    f"(ckpt step {step_before})"
                )
                self._child = self._popen(self.cmd, env=env)
                child_pid = getattr(self._child, "pid", None)
                rc = self._child.wait()
                crumb = read_breadcrumb(self.workdir)
                if (
                    crumb is not None
                    and child_pid is not None
                    and crumb.get("pid") != child_pid
                ):
                    # Stale: written by a PREVIOUS attempt's process.  A
                    # child that crashed before its first breadcrumb (bad
                    # config, import error) must not inherit the old
                    # phase — a crash misread as "preempted" would reset
                    # the crash-loop counter forever.  (A launcher that
                    # forks before exec'ing python breaks the pid match;
                    # classification then falls back to the exit status,
                    # which still carries 42/43 through a forwarding
                    # shell.)
                    crumb = None
                cause = classify_exit(rc, crumb)
                step_after = latest_checkpoint_step(self.ckpt_dir)
                progressed = step_after is not None and (
                    step_before is None or step_after > step_before
                )
                self._log(
                    {
                        "kind": "supervisor_attempt",
                        "attempt": attempt,
                        "rc": rc,
                        "cause": cause,
                        "breadcrumb_phase": (crumb or {}).get("phase"),
                        "ckpt_step_before": step_before,
                        "ckpt_step_after": step_after,
                        "progressed": progressed,
                    }
                )
                self._say(
                    f"attempt {attempt}: exit {rc} ({cause}), checkpoint "
                    f"{step_before} -> {step_after}"
                )
                if cause == "clean":
                    return SupervisorResult(EXIT_CLEAN, attempt + 1, restarts)
                if self._stop.is_set():
                    # The operator/scheduler preempted the whole unit: the
                    # child already ran its graceful path; do not relaunch.
                    return SupervisorResult(
                        rc, attempt + 1, restarts,
                        reason="stopped by signal",
                    )
                attempt += 1
                # Only a restart that PROGRESSED, or a preemption whose
                # breadcrumb confirms the graceful path completed (phase
                # "preempted" — the emergency checkpoint is durable),
                # resets the no-progress streak.  A 43 whose grace window
                # expired (phase "preempt_timeout", e.g. a dead checkpoint
                # store) must keep counting toward backoff + give-up, or a
                # persistently failing graceful path relaunches in a tight
                # loop forever.
                graceful = (
                    cause == "preempted"
                    and (crumb or {}).get("phase") == "preempted"
                )
                decision = self.policy.record_exit(progressed or graceful)
                if decision != "restart":
                    if decision == "give_up_crash_loop":
                        msg = (
                            f"crash loop: {self.policy.fail_streak} "
                            f"consecutive exits ({cause} last, rc {rc}) "
                            f"without checkpoint progress (stuck at step "
                            f"{step_after}) — giving up. "
                            f"Fix the run; restarting cannot."
                        )
                    else:
                        msg = f"restart budget exhausted ({self.max_restarts})"
                    self._say(msg)
                    self._log(
                        {
                            "kind": "supervisor_give_up",
                            "severity": "critical",
                            "message": msg,
                            "attempts": attempt,
                            "rc": rc,
                        }
                    )
                    return SupervisorResult(
                        rc, attempt, restarts, gave_up=True, reason=msg
                    )
                restarts[cause] = restarts.get(cause, 0) + 1
                self._restarts.inc(cause=cause)
                delay = self.policy.delay_s()
                if delay > 0:
                    self._say(
                        f"backing off {delay:.2f}s (no-progress streak "
                        f"{self.policy.fail_streak})"
                    )
                    self._sleep(delay)
        finally:
            self._child = None
            for sig, prev in installed:
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ddlpc_tpu.resilience.supervisor",
        description="Supervise a training command: restart on stall/crash/"
        "preemption, resume from checkpoints, give up on crash loops.",
    )
    p.add_argument("--workdir", required=True, help="run directory (breadcrumb, resilience.jsonl, checkpoints/)")
    p.add_argument("--ckpt-dir", help="checkpoint dir (default <workdir>/checkpoints)")
    p.add_argument("--max-restarts", type=int, default=100)
    p.add_argument("--crash-loop-limit", type=int, default=3)
    p.add_argument("--backoff-base-s", type=float, default=1.0)
    p.add_argument("--backoff-cap-s", type=float, default=60.0)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- then the training command to supervise")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no command given (put it after --)")
    sup = Supervisor(
        cmd,
        workdir=args.workdir,
        ckpt_dir=args.ckpt_dir,
        max_restarts=args.max_restarts,
        crash_loop_limit=args.crash_loop_limit,
        backoff_base_s=args.backoff_base_s,
        backoff_cap_s=args.backoff_cap_s,
    )
    result = sup.run()
    return 0 if result.ok else (result.final_status if result.final_status > 0 else 1)


if __name__ == "__main__":
    sys.exit(main())
