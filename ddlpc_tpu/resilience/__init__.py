"""Resilience subsystem: the detect → abort → restart → resume story as
shipped product (docs/RESILIENCE.md).

The reference hangs forever on a dead peer (кластер.py:215-220, SURVEY §5)
and has no checkpoint to come back to.  This package closes the loop the
repo already had the pieces for:

- :mod:`protocol` — the structured exit-status + breadcrumb contract
  between a training process and whatever supervises it;
- :mod:`supervisor` — a process supervisor that relaunches training with
  exponential backoff + jitter, distinguishes exit causes, detects crash
  loops, and emits ``ddlpc_restarts_total{cause}``;
- :mod:`chaos` — env-var-driven fault injection (kill, stall, NaN loss,
  checkpoint bit-flip, disk-full, slow loader) used by the tests and
  ``scripts/chaos_soak.py``.
"""

from ddlpc_tpu.resilience.protocol import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_PREEMPTED,
    EXIT_STALL,
    read_breadcrumb,
    write_breadcrumb,
)
from ddlpc_tpu.resilience.supervisor import (  # noqa: F401
    Supervisor,
    SupervisorResult,
    classify_exit,
)
