"""Env-var-driven fault injection — the chaos harness behind the soak test.

A fault schedule is one string in ``DDLPC_CHAOS``, semicolon-separated:

  ``kill@N``        SIGKILL this process at train step N (no cleanup at
                    all — the hard-crash case)
  ``stall@N[:S]``   sleep S seconds (default 3600) at step N with no
                    heartbeat — the hung-collective case the watchdog
                    turns into EXIT_STALL
  ``preempt@N``     request graceful preemption at step N — deterministic
                    SIGTERM-equivalent without signal-delivery races
  ``nan@N``         at step N, poison the next epoch record's loss with
                    NaN (drives the obs/health.py critical alert)
  ``flip_ckpt@K``   flip one byte in the blob of the Kth checkpoint write
                    — the on-disk corruption the CRC manifest must catch
  ``disk_full@K``   the Kth checkpoint write raises ENOSPC before writing
                    — surfaces through the AsyncCheckpointer's
                    re-raise-on-training-thread contract
  ``slow_loader:MS``  every data fetch sleeps MS milliseconds

Serve-side faults (ISSUE 10), triggered by batched-forward count in the
serving engine instead of optimizer steps:

  ``serve_kill@N``    SIGKILL the serving process at its Nth batched
                      forward — the replica-death case the fleet router
                      must retry around
  ``serve_stall@N[:S]``  sleep S seconds (default 60) inside the Nth
                      forward — the response-stall case that must surface
                      as a router timeout, not a hung client
  ``serve_err@N[:K]`` raise :class:`ChaosFault` from forwards N..N+K-1
                      (default K=1) — the error burst that must trip the
                      router's per-replica circuit breaker
  ``reload_corrupt@K``  before the Kth checkpoint hot-reload, flip one
                      byte of the newest checkpoint blob — the reader
                      quarantines it and falls back, which a rolling
                      fleet reload must treat as a fleet-wide abort

Step numbers count optimizer-step loop iterations **since process start**
(a restarted process counts from 0 again — the supervisor's per-attempt
``env_fn`` is how a schedule avoids re-killing itself forever); serve
triggers count batched forwards since process start the same way.
One-shot faults fire at most once per process.  Injections print a
``[chaos]`` line to stderr so a survival report can be audited against
the schedule.

Stdlib-only on purpose: ``train/checkpoint.py`` calls the checkpoint hooks
and must not gain a heavyweight (or circular) import for a harness that is
inert unless the env var is set.
"""

from __future__ import annotations

import errno
import os
import signal
import sys
import time
from typing import Dict, List, Optional, Set

ENV = "DDLPC_CHAOS"

_cache_spec: Optional[str] = None
_cache_monkey: Optional["ChaosMonkey"] = None


class ChaosError(ValueError):
    """A malformed DDLPC_CHAOS spec — raised at parse time, loudly, so a
    typo'd schedule cannot silently run a chaos-free soak."""


class ChaosFault(RuntimeError):
    """An injected serve-side failure (``serve_err``): raised out of the
    engine's forward so it rides the real error path — batcher fails the
    batch, frontend answers 500, the router's breaker counts it."""


def _log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


class ChaosMonkey:
    """Parsed fault schedule + one-shot firing state for this process."""

    KINDS = (
        "kill", "stall", "preempt", "nan", "flip_ckpt", "disk_full",
        "slow_loader", "serve_kill", "serve_stall", "serve_err",
        "reload_corrupt",
    )

    def __init__(self, spec: str):
        self.spec = spec
        # kind -> trigger (step or nth-event); stall also keeps a duration.
        self.step_faults: Dict[int, List[dict]] = {}
        self.ckpt_faults: Dict[str, int] = {}  # kind -> nth write (1-based)
        # Serve-side: nth batched forward -> faults; nth reload -> corrupt.
        self.serve_faults: Dict[int, List[dict]] = {}
        self.reload_corrupt_at = 0  # 1-based reload count; 0 = unscheduled
        self.slow_loader_ms = 0.0
        self.fired: List[dict] = []
        self._nan_armed = False
        self._ckpt_writes = 0
        self._serve_forwards = 0
        self._reloads = 0
        self._err_burst_left = 0
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            self._parse(part)

    def _parse(self, part: str) -> None:
        dur = None
        if ":" in part:
            part, _, tail = part.partition(":")
            try:
                dur = float(tail)
            except ValueError:
                raise ChaosError(f"bad duration in chaos fault {part!r}:{tail!r}")
        if part.startswith("slow_loader"):
            if dur is None:
                raise ChaosError("slow_loader needs :MS, e.g. slow_loader:50")
            self.slow_loader_ms = dur
            return
        kind, sep, at = part.partition("@")
        if not sep or kind not in self.KINDS:
            raise ChaosError(
                f"unknown chaos fault {part!r} (kinds: {', '.join(self.KINDS)})"
            )
        try:
            n = int(at)
        except ValueError:
            raise ChaosError(f"bad trigger in chaos fault {part!r}")
        if kind in ("flip_ckpt", "disk_full"):
            self.ckpt_faults[kind] = n
        elif kind == "reload_corrupt":
            self.reload_corrupt_at = n
        elif kind.startswith("serve_"):
            self.serve_faults.setdefault(n, []).append(
                {"kind": kind, "dur": dur}
            )
        else:
            self.step_faults.setdefault(n, []).append(
                {"kind": kind, "dur": dur}
            )

    # -- hooks (all no-ops unless a matching fault is scheduled) ------------

    def on_step(self, step: int) -> Set[str]:
        """Called once per optimizer-step loop iteration.  ``kill`` and
        ``stall`` act here; ``preempt``/``nan`` are returned/armed for the
        trainer to act on (preemption must run the trainer's own graceful
        path — that is the point of the fault)."""
        faults = self.step_faults.pop(step, None)
        actions: Set[str] = set()
        if not faults:
            return actions
        for f in faults:
            kind = f["kind"]
            self.fired.append({"kind": kind, "step": step})
            _log(f"{kind} at step {step}")
            if kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif kind == "stall":
                time.sleep(f["dur"] if f["dur"] is not None else 3600.0)
            elif kind == "nan":
                self._nan_armed = True
            else:
                actions.add(kind)
        return actions

    def on_data_fetch(self) -> None:
        if self.slow_loader_ms > 0:
            time.sleep(self.slow_loader_ms / 1000.0)

    def corrupt_record(self, record: dict) -> dict:
        """Armed by ``nan@N``: poison the loss of the next epoch record."""
        if self._nan_armed and "loss" in record:
            self._nan_armed = False
            self.fired.append({"kind": "nan_record"})
            _log("poisoning epoch record loss with NaN")
            record = dict(record, loss=float("nan"))
        return record

    def on_checkpoint_save(self) -> None:
        """Before a checkpoint blob write; raises ENOSPC on the scheduled
        write.  The counter counts save ATTEMPTS, so the failing write and
        a flip on a later write can share one schedule."""
        self._ckpt_writes += 1
        if self.ckpt_faults.get("disk_full") == self._ckpt_writes:
            del self.ckpt_faults["disk_full"]
            self.fired.append(
                {"kind": "disk_full", "write": self._ckpt_writes}
            )
            _log(f"injecting ENOSPC on checkpoint write {self._ckpt_writes}")
            raise OSError(errno.ENOSPC, "chaos: no space left on device")

    def on_checkpoint_written(self, path: str) -> None:
        """After a blob landed under its final name: flip one mid-file byte
        on the scheduled write — exactly the corruption the per-chunk CRCs
        (train/checkpoint.py) must catch and quarantine on restore."""
        if self.ckpt_faults.get("flip_ckpt") != self._ckpt_writes:
            return
        del self.ckpt_faults["flip_ckpt"]
        try:
            size = os.path.getsize(path)
            pos = size // 2
            with open(path, "r+b") as f:
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([b[0] ^ 0xFF]))
            self.fired.append(
                {"kind": "flip_ckpt", "path": path, "offset": pos}
            )
            _log(f"flipped byte {pos} of {path}")
        except OSError as e:
            _log(f"flip_ckpt failed on {path}: {e}")

    # -- serve-side hooks (ISSUE 10) ----------------------------------------

    def on_serve_forward(self) -> None:
        """Called once per batched forward in the serving engine
        (serve/engine.py:forward_windows).  ``serve_kill`` and
        ``serve_stall`` act in place; ``serve_err`` arms a burst of
        :class:`ChaosFault` raises covering this and the next K-1
        forwards — the real 500 path the router's breaker must count."""
        self._serve_forwards += 1
        n = self._serve_forwards
        for f in self.serve_faults.pop(n, ()):
            kind = f["kind"]
            self.fired.append({"kind": kind, "forward": n})
            _log(f"{kind} at forward {n}")
            if kind == "serve_kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif kind == "serve_stall":
                time.sleep(f["dur"] if f["dur"] is not None else 60.0)
            elif kind == "serve_err":
                self._err_burst_left = int(f["dur"] or 1)
        if self._err_burst_left > 0:
            self._err_burst_left -= 1
            raise ChaosFault(f"chaos: injected error burst (forward {n})")

    def on_serve_reload(self, ckpt_dir: str) -> None:
        """Called at the top of every checkpoint hot-reload; on the Kth,
        flips one mid-file byte of the NEWEST live blob so the CRC reader
        quarantines it and falls back — the corrupt-reload case a rolling
        fleet update must abort on."""
        self._reloads += 1
        if self.reload_corrupt_at != self._reloads:
            return
        self.reload_corrupt_at = 0
        from ddlpc_tpu.resilience.protocol import _CKPT_RE

        try:
            names = [n for n in os.listdir(ckpt_dir) if _CKPT_RE.match(n)]
        except OSError as e:
            _log(f"reload_corrupt: cannot list {ckpt_dir}: {e}")
            return
        if not names:
            _log(f"reload_corrupt: no checkpoints in {ckpt_dir}")
            return
        newest = max(names, key=lambda n: int(_CKPT_RE.match(n).group(1)))
        path = os.path.join(ckpt_dir, newest)
        try:
            size = os.path.getsize(path)
            pos = size // 2
            with open(path, "r+b") as f:
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([b[0] ^ 0xFF]))
            self.fired.append(
                {"kind": "reload_corrupt", "path": path, "offset": pos,
                 "reload": self._reloads}
            )
            _log(f"reload_corrupt: flipped byte {pos} of {path}")
        except OSError as e:
            _log(f"reload_corrupt failed on {path}: {e}")


def active() -> Optional[ChaosMonkey]:
    """The process's ChaosMonkey, or None when ``DDLPC_CHAOS`` is unset.

    One instance per distinct spec value: one-shot firing state persists
    across call sites (trainer step loop, checkpoint writer), and a test
    that rewrites the env var gets a fresh schedule.
    """
    global _cache_spec, _cache_monkey
    spec = os.environ.get(ENV)
    if not spec:
        _cache_spec, _cache_monkey = None, None
        return None
    if spec != _cache_spec:
        _cache_monkey = ChaosMonkey(spec)
        _cache_spec = spec
    return _cache_monkey
