"""Wire-compressed ring all-reduce: quantized bytes on the interconnect.

The reference's research contribution is sending FEWER BYTES for gradient
synchronization over a slow link: workers int8/fp16-quantize gradients before
the TCP send and the server re-quantizes the averaged gradient for the
broadcast (кластер.py:450-503, 328-396).  The framework's default codec path
(`grad_sync.sync_gradients`) reproduces that scheme's *information loss*
inside a plain `lax.pmean` — semantically exact, but the all-reduce itself
still moves fp32 over ICI/DCN, because XLA's collectives have no quantized
wire format.

This module moves the actual wire bytes: a hand-written ring
reduce-scatter + ring all-gather built from `lax.ppermute`, where every hop
transfers the smallest integer dtype that can hold the running partial sum —
int8 when ``axis_size * levels <= 127`` (the reference's ±10-level int8 codec
on an 8-way mesh sends exactly 1 byte/element/hop, 4× less than fp32),
int16 otherwise.  On DCN-bound multi-host meshes, where link bandwidth is
the constraint the reference designed for, this is the TPU-native
realization of its compressed transport; within one ICI slice the native
fp32 `psum` is usually faster and remains the default.

Quantization semantics (mirroring the reference's two loss points):
- one *shared* scale = `pmax` of the per-replica global absmax (the
  reference uses each worker's own absmax, кластер.py:463-471; a shared
  scale is required for integer summation on the wire — per-element error
  stays bounded by the shared scale, which may exceed a replica's local
  absmax and hence enlarge that replica's quantization step vs the
  reference's per-worker scale);
- each replica quantizes once before the reduce (client wire,
  кластер.py:474-496) — the integer partial sums then accumulate EXACTLY,
  unlike float wire formats;
- the averaged chunk is re-quantized once for the all-gather hops (server
  rebroadcast, кластер.py:328-396), so every replica decodes bit-identical
  mean gradients — the reference's self-application guarantee
  (кластер.py:402-433) by construction.

Total per-element error: with ``rounding='nearest'`` ≤ scale/levels (one
half-step per quantization, two quantizations); with
``rounding='stochastic'`` each quantization can miss by up to a FULL step
(the draw is unbiased, not nearest), so the worst case is 2·scale/levels.
Either way this matches the simulate path's bound for the same rounding
mode with ``quantize_local=quantize_mean=True``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddlpc_tpu.config import CompressionConfig

PyTree = Any


def _ring_perm(axis_size: int) -> List[Tuple[int, int]]:
    """Unidirectional ring: rank i sends to rank (i+1) % N."""
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def wire_dtype(axis_size: int, levels: int) -> jnp.dtype:
    """Smallest integer dtype holding any ring partial sum (≤ N·levels).

    Raises when only int32 would fit: 4-byte hops are the same wire bytes as
    the native fp32 psum, so the ring would add 2(N-1) hops of latency for
    zero compression — use transport='simulate' (or fewer levels) there."""
    peak = axis_size * levels
    if peak <= 127:
        return jnp.int8
    if peak <= 32767:
        return jnp.int16
    raise ValueError(
        f"ring transport with {levels} levels on {axis_size} replicas needs "
        f"int32 hops (peak partial sum {peak}) — that moves the same bytes "
        "as the native fp32 all-reduce; use transport='simulate' instead"
    )


def ring_wire_report(num_elements: int, axis_size: int, cfg: CompressionConfig) -> dict:
    """Exact wire-byte accounting for one ring all-reduce vs the fp32 ring.

    The reference logs its compressed payload sizes every sync
    (кластер.py:47-52,116); this is the framework's equivalent evidence that
    the compressed transport actually moves fewer interconnect bytes — the
    numbers are computed from the algorithm (dtype × chunk × hops), not
    asserted.  Per replica: 2(N-1) hops (reduce-scatter + all-gather), each
    carrying one ceil(n/N)-element chunk in the wire dtype; the fp32
    baseline is the same ring algorithm at 4 bytes/element (bandwidth-
    optimal all-reduce moves ~2n bytes/replica regardless of topology, so
    the ratio holds against any fp32 collective, not just a ring).
    """
    from ddlpc_tpu.ops.quantize import levels_for

    if cfg.mode == "none":
        wdt, itemsize = jnp.float32, 4  # exact pmean fallback: fp32 wire
    else:
        wdt = wire_dtype(axis_size, int(levels_for(cfg)))
        itemsize = jnp.dtype(wdt).itemsize
    chunk = -(-num_elements // axis_size)
    hops = 2 * (axis_size - 1)
    return {
        "elements": num_elements,
        "axis_size": axis_size,
        "wire_dtype": str(jnp.dtype(wdt)),
        "hops_per_replica": hops,
        "bytes_per_hop": chunk * itemsize,
        "wire_bytes_per_replica": hops * chunk * itemsize,
        "fp32_bytes_per_replica": hops * chunk * 4,
        "compression_ratio": 4.0 / itemsize,
    }


def _flatten(tree: PyTree) -> Tuple[jax.Array, List[Any], Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [l.shape for l in leaves]
    flat = jnp.concatenate([l.astype(jnp.float32).ravel() for l in leaves])
    return flat, shapes, treedef

def _unflatten(flat: jax.Array, shapes: Sequence[Any], treedef: Any) -> PyTree:
    out, offset = [], 0
    for shape in shapes:
        size = int(np.prod(shape)) if shape else 1
        out.append(lax.dynamic_slice_in_dim(flat, offset, size).reshape(shape))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)


def ring_allreduce_mean_quantized(
    tree: PyTree,
    axis_name: str,
    axis_size: int,
    cfg: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> PyTree:
    """Mean ``tree`` across ``axis_name`` with quantized bytes on every hop.

    Must be called inside `shard_map`/`pmap` over an axis of (static) size
    ``axis_size``.  ``cfg.mode`` selects the level count exactly as the
    simulate-path codec does ('int8' → ±int8_levels, 'float16' →
    ±fp16_levels); 'none' falls back to an exact `lax.pmean`.
    """
    from ddlpc_tpu.ops.quantize import (
        fake_quantize,
        levels_for,
        quantize_with_scale,
        rounding_key,
        safe_divisor,
        snap_to_lattice,
    )

    if cfg.mode == "none":
        return lax.pmean(tree, axis_name)
    if not jax.tree_util.tree_leaves(tree):
        return tree
    key = rounding_key(cfg, key)
    local_key = mean_key = None
    if key is not None:
        local_key, mean_key = jax.random.split(key)
        # Per-replica noise for the local quantization (correlated noise
        # would survive the mean at full-step size — see grad_sync.py); the
        # mean requantization keeps the shared key so the gathered chunks
        # are bit-identical however they were produced.
        local_key = jax.random.fold_in(local_key, lax.axis_index(axis_name))
    if axis_size == 1:
        # Single replica: the mean is the identity; apply the codec's two
        # quantization points so semantics match the N>1 path — through
        # the same fences as every other codec site in parallel/, so the
        # bits cannot depend on what XLA fuses around this degenerate arm.
        from ddlpc_tpu.parallel.grad_sync import apply_codec_fenced

        return apply_codec_fenced(
            fake_quantize,
            apply_codec_fenced(fake_quantize, tree, cfg, key=local_key),
            cfg,
            key=mean_key,
        )

    levels = float(levels_for(cfg))
    flat, shapes, treedef = _flatten(tree)
    n = flat.shape[0]

    # Shared scale: max over replicas of the whole-model absmax.  One scalar
    # collective — negligible next to the gradient payload.
    scale = lax.pmax(jnp.max(jnp.abs(flat)), axis_name)
    safe = safe_divisor(scale)

    # Quantize ONCE per replica (client-wire loss point, кластер.py:474-496).
    q = quantize_with_scale(flat, safe, levels, key=local_key)

    # Pad so the vector splits into axis_size equal chunks.
    chunk = -(-n // axis_size)  # ceil
    q = jnp.pad(q, (0, chunk * axis_size - n)).reshape(axis_size, chunk)

    wdt = wire_dtype(axis_size, int(levels))
    perm = _ring_perm(axis_size)
    rank = lax.axis_index(axis_name)

    # --- ring reduce-scatter (N-1 hops, integer partial sums: EXACT) -------
    # Invariant: after k hops the travelling partial at rank r covers chunk
    # (r + 1 - k) mod N summed over ranks r-k..r.  After N-1 hops rank r owns
    # the full sum of chunk (r + 2) mod N.
    own0 = (rank + 1) % axis_size
    partial = lax.dynamic_index_in_dim(q, own0, keepdims=False)
    for k in range(1, axis_size):
        partial = lax.ppermute(partial.astype(wdt), axis_name, perm)
        idx = (rank + 1 - k) % axis_size
        partial = partial.astype(jnp.float32) + lax.dynamic_index_in_dim(
            q, idx, keepdims=False
        )
    own = (rank + 2) % axis_size

    # Mean, then re-quantize ONCE for the broadcast hops (server-rebroadcast
    # loss point, кластер.py:328-396).  |mean| ≤ scale, so the same scale is
    # valid and the gather hops carry signed values ≤ levels: int8 always
    # suffices here, but we keep ``wdt`` for a single wire format.  The mean
    # is already in lattice units (value·levels/scale), so snap it directly
    # (nearest or stochastic per the shared key).
    mean_q = snap_to_lattice(partial / axis_size, levels, key=mean_key).astype(wdt)

    # --- ring all-gather of the quantized mean chunks (N-1 hops) -----------
    out = jnp.zeros((axis_size, chunk), wdt)
    out = lax.dynamic_update_index_in_dim(out, mean_q, own, axis=0)
    travelling = mean_q
    for k in range(1, axis_size):
        travelling = lax.ppermute(travelling, axis_name, perm)
        idx = (rank - k + 2) % axis_size  # chunk owned by rank r-k
        out = lax.dynamic_update_index_in_dim(out, travelling, idx, axis=0)

    # Runtime-scalar multiply, quantize.decode's formula — the constant-
    # divisor form is not LLVM-rewrite-stable across programs (see decode).
    mean_flat = out.reshape(-1)[:n].astype(jnp.float32) * (scale / levels)
    return _unflatten(mean_flat, shapes, treedef)
