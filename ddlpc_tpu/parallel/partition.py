"""Declarative regex partition rules for the ZeRO state layouts.

PR 5's ZeRO-1 built its placement leaf by leaf: ``chunkable`` decided
which optimizer leaves shard by shape, ``zero_leaf_spec`` hand-picked a
GSPMD dimension, and the checkpoint gather/shard paths re-derived both.
Extending that to ZeRO-2 (gradients persist sharded) and ZeRO-3 (params
persist sharded) would triple the ad-hoc sites — exactly the drift PR 13
had to debug when ``zero_leaf_spec`` picked uneven dims.  This module
replaces all of it with the ``match_partition_rules`` /
``make_shard_and_gather_fns`` pattern (SNIPPETS.md [2], the pjit-era
idiom of arxiv 2204.06514): an ordered table of ``(regex,
PartitionSpec)`` rules over **named flattened leaves** is the single
owner of every placement decision, and ``StateLayout``, the GSPMD
builders, the HBM gauges and the checkpoint shard/gather fns all read
the same :class:`Decision` tree.

Naming: a leaf's name is its "/"-joined tree path, e.g.
``opt_state/0/mu/Conv_0/kernel`` or ``params/ConvBlock_2/Conv_0/bias``.
Rules are tried in order; the FIRST ``re.search`` match wins; a leaf no
rule matches is an error (a silent default is how leaves end up
replicated by accident — the failure mode the PR 13 sharding contract
exists to catch).  A rule's spec is either a concrete
``PartitionSpec`` or the :data:`SHARD` sentinel, which resolves
per-layout:

- **chunk mode** (shard_map layouts zero1/zero2/zero3): the leaf is
  flattened to the ``[N, K]`` chunk view (``shard_update.chunk_leaf``)
  and sharded ``P(data)`` on the chunk axis — every leaf chunks, so the
  only fallback is ``not-param-shaped`` (step counters, schedule
  scalars).
- **leaf mode** (GSPMD layouts): :func:`even_shard_spec` partitions the
  largest dimension that divides evenly by the data-axis size; a leaf
  with no such dimension stays replicated with the explicit reason
  ``replicated-by-rule`` — a budgeted decision the sharding contract and
  the ``ddlpc_hbm_replicated_by_rule_bytes`` gauge can see, not a
  silent special case.

Tier: ``jax`` (analysis/tiers.py) — jax.tree walks and PartitionSpec
construction only; nothing here launches a computation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

PyTree = Any


class _ShardSentinel:
    """Marker spec: "shard this leaf, the layout picks how" — chunk view
    in the shard_map layouts, :func:`even_shard_spec` under GSPMD."""

    def __repr__(self) -> str:  # pragma: no cover - repr cosmetics
        return "SHARD"


SHARD = _ShardSentinel()

# Decision.reason values — why a leaf got its spec.
REASON_RULE = "rule"                        # concrete spec straight from a rule
REASON_AUTO = "auto-shard"                  # SHARD resolved to a sharded spec
REASON_REPLICATED_BY_RULE = "replicated-by-rule"  # SHARD, but no even dim
REASON_NOT_PARAM_SHAPED = "not-param-shaped"      # SHARD, but not a tensor the
#                                                   param-shape safety gate accepts


@dataclass(frozen=True)
class Rule:
    """One ordered partition rule: ``re.search(pattern, leaf_name)``."""

    pattern: str
    spec: Any  # PartitionSpec | SHARD


@dataclass(frozen=True)
class Decision:
    """The resolved placement of one named leaf — the audit trail every
    consumer (StateLayout, GSPMD constraints, HBM gauges, checkpoint
    fns) reads instead of re-deriving placement."""

    name: str
    shape: Tuple[int, ...]
    spec: P
    rule: Optional[str]  # the pattern that matched (None never happens —
    #                      a no-match is an error, not a decision)
    reason: str

    @property
    def sharded(self) -> bool:
        return any(ax is not None for ax in tuple(self.spec))


# ---------------------------------------------------------------------------
# leaf naming


def _key_str(key) -> str:
    """One path entry -> its name segment ('/'-joined by callers)."""
    tu = jax.tree_util
    if isinstance(key, tu.DictKey):
        return str(key.key)
    if isinstance(key, tu.SequenceKey):
        return str(key.idx)
    if isinstance(key, tu.GetAttrKey):
        return str(key.name)
    if isinstance(key, tu.FlattenedIndexKey):
        return str(key.key)
    return str(key)


def leaf_name(prefix: str, path) -> str:
    segs = [_key_str(k) for k in path]
    return "/".join(([prefix] if prefix else []) + segs)


def named_leaves(tree: PyTree, prefix: str = "") -> List[Tuple[str, Any]]:
    """Flatten ``tree`` to ``[(name, leaf)]`` with "/"-joined path names
    (``prefix`` prepended) — the namespace the rule table matches."""
    return [
        (leaf_name(prefix, path), leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
    ]


# ---------------------------------------------------------------------------
# rule matching


def match_partition_rules(rules: Sequence[Rule], name: str):
    """First rule whose pattern ``re.search``-matches ``name``.  A leaf
    no rule covers is a hard error: the table must be total (end it with
    ``Rule(".*", P())``), so an unplaced leaf is a missing-rule bug, not
    a silent replication."""
    for rule in rules:
        if re.search(rule.pattern, name):
            return rule
    raise ValueError(
        f"no partition rule matches leaf {name!r} — the rule table must "
        f"be total (end it with Rule('.*', P()))"
    )


def even_shard_spec(
    shape: Tuple[int, ...], n_shards: int, data_axis: str
) -> P:
    """GSPMD auto-placement for a SHARD-matched leaf: partition the
    largest dimension that divides EVENLY by the data axis; no such
    dimension -> ``P()`` (the caller records ``replicated-by-rule``).
    An uneven pick used to fall back to the largest dimension >= N on
    the theory that GSPMD pads — but an uneven NamedSharding is rejected
    by ``jit in_shardings`` at the state boundary, so any model with
    e.g. a 6-class bias on a 4-way mesh crashed at placement (surfaced
    by the compiled-program auditor, docs/ANALYSIS.md)."""
    if not shape:
        return P()
    pick = None
    for d in sorted(range(len(shape)), key=lambda d: shape[d], reverse=True):
        if shape[d] >= n_shards and shape[d] % n_shards == 0:
            pick = d
            break
    if pick is None:
        return P()
    spec = [None] * len(shape)
    spec[pick] = data_axis
    return P(*spec)


def decide(
    rules: Sequence[Rule],
    name: str,
    shape: Tuple[int, ...],
    *,
    mode: str,
    n_shards: int,
    data_axis: str,
    param_shaped: bool = True,
) -> Decision:
    """Resolve one named leaf against the rule table.

    ``mode='chunk'``: SHARD -> ``P(data_axis)`` over the leaf's chunk
    view.  ``mode='leaf'``: SHARD -> :func:`even_shard_spec`.
    ``param_shaped`` is the shape-based safety gate the chunk layout has
    always had (a SHARD-matched leaf that is not parameter-shaped — a
    step counter a too-broad rule caught — stays replicated with its own
    reason rather than corrupting the chunk arithmetic)."""
    if mode not in ("chunk", "leaf"):
        raise ValueError(f"unknown partition mode {mode!r}")
    shape = tuple(int(d) for d in shape)
    rule = match_partition_rules(rules, name)
    if not isinstance(rule.spec, _ShardSentinel):
        return Decision(name, shape, rule.spec, rule.pattern, REASON_RULE)
    if not param_shaped:
        return Decision(name, shape, P(), rule.pattern,
                        REASON_NOT_PARAM_SHAPED)
    if mode == "chunk":
        return Decision(name, shape, P(data_axis), rule.pattern, REASON_AUTO)
    spec = even_shard_spec(shape, n_shards, data_axis)
    reason = (
        REASON_AUTO if any(ax is not None for ax in tuple(spec))
        else REASON_REPLICATED_BY_RULE
    )
    return Decision(name, shape, spec, rule.pattern, reason)


def decide_tree(
    rules: Sequence[Rule],
    tree: PyTree,
    prefix: str,
    *,
    mode: str,
    n_shards: int,
    data_axis: str,
    pshapes: Optional[frozenset] = None,
) -> PyTree:
    """Map :func:`decide` over a tree -> same-structure tree of
    :class:`Decision`.  ``pshapes`` (the parameter-shape set) feeds the
    param-shaped safety gate; ``None`` disables it (params/grads trees
    are param-shaped by construction)."""

    def one(path, leaf):
        shape = tuple(int(d) for d in leaf.shape)
        param_shaped = True
        if pshapes is not None:
            param_shaped = len(shape) > 0 and shape in pshapes
        return decide(
            rules, leaf_name(prefix, path), shape,
            mode=mode, n_shards=n_shards, data_axis=data_axis,
            param_shaped=param_shaped,
        )

    return jax.tree_util.tree_map_with_path(one, tree)


# ---------------------------------------------------------------------------
# the state-wide rule tables


def state_partition_rules(level: str, data_axis: str = "data") -> Tuple[Rule, ...]:
    """The ZeRO ladder as ONE ordered rule table over TrainState leaf
    names (``params/...``, ``grads/...``, ``opt_state/...``; the grads
    namespace is the optimizer-boundary gradient — what persists between
    the wire collective and the update).

    =========  ======================================================
    level      what shards (everything else replicated by the catch-all)
    =========  ======================================================
    zero1      optimizer moments (``mu``/``nu``/``trace``)
    zero2      + gradients (they arrive reduce-scattered and stay so)
    zero3      + parameters (gathered on demand per step)
    =========  ======================================================

    Precedence is positional: first match wins, and the table always
    ends with the total catch-all ``Rule('.*', P())`` so every leaf gets
    an explicit decision."""
    if level not in ("replicated", "zero1", "zero2", "zero3"):
        raise ValueError(
            f"unknown ZeRO level {level!r} "
            f"(expected replicated|zero1|zero2|zero3)"
        )
    del data_axis  # placement axis is resolved by decide(), not the table
    rules: List[Rule] = []
    if level == "zero3":
        rules.append(Rule(r"^params/", SHARD))
    if level in ("zero2", "zero3"):
        rules.append(Rule(r"^grads/", SHARD))
    if level != "replicated":
        rules.append(Rule(r"^opt_state/(.*/)?(mu|nu|trace)(/|$)", SHARD))
    rules.append(Rule(r".*", P()))
    return tuple(rules)


def replicated_by_rule_bytes(decisions: PyTree, tree: PyTree) -> int:
    """Per-device bytes of leaves the rule engine DECIDED to replicate
    (``replicated-by-rule``) — the explicit HBM budget line the PR 13
    sharding contract and the ``ddlpc_hbm`` gauges charge instead of
    special-casing uneven leaves."""
    total = 0
    for d, leaf in zip(jax.tree.leaves(decisions), jax.tree.leaves(tree)):
        if d.reason != REASON_REPLICATED_BY_RULE:
            continue
        n = 1
        for s in leaf.shape:
            n *= int(s)
        total += n * jax.numpy.dtype(leaf.dtype).itemsize
    return total


# ---------------------------------------------------------------------------
# pipeline stage rules (docs/SHARDING.md "Pipeline stages")
#
# The same declarative pattern as the ZeRO tables, one level up: an ordered
# (regex, stage_index) table over named flattened leaves is the single owner
# of which pipeline stage holds each parameter.  First match wins; a leaf no
# rule covers raises — an unassigned leaf is a missing-rule bug, not a
# silently replicated straggler (the exact failure mode the ZeRO tables
# exist to prevent).


@dataclass(frozen=True)
class StageRule:
    """One ordered stage-assignment rule: ``re.search(pattern, leaf_name)``
    → the leaf lives on pipeline stage ``stage``."""

    pattern: str
    stage: int


def match_stage_rules(rules: Sequence[StageRule], name: str) -> int:
    for rule in rules:
        if re.search(rule.pattern, name):
            return rule.stage
    raise ValueError(
        f"no stage rule matches leaf {name!r} — the stage table must cover "
        f"every parameter (parallel/pipeline.py builds it from the model's "
        f"block list; an uncovered leaf means the cut and the model "
        f"disagree)"
    )


def stage_rules_for_blocks(
    block_names: Sequence[str], assignment: Sequence[int]
) -> Tuple[StageRule, ...]:
    """One rule per top-level block, anchored to the START of the leaf
    path (``^{block}/``): block names recur nested (every DownBlock/
    UpBlock holds an inner ``DoubleConv_0``), so a float-anchored
    ``(^|/)`` would let the bottleneck's rule steal decoder leaves —
    only the top-level module name decides the stage."""
    if len(block_names) != len(assignment):
        raise ValueError("block_names and assignment length mismatch")
    return tuple(
        StageRule(rf"^{re.escape(b)}/", int(s))
        for b, s in zip(block_names, assignment)
    )


def balanced_stage_assignment(
    block_bytes: Sequence[int], n_stages: int
) -> List[int]:
    """Contiguous partition of the ordered block list into ``n_stages``
    groups minimizing the max per-stage byte share (classic linear
    partition DP — block counts are tiny).  Contiguity is load-bearing:
    a pipeline stage must be a contiguous slice of the execution order so
    one activation carry crosses each boundary.  Returns the per-block
    stage index, non-decreasing."""
    n = len(block_bytes)
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_stages > n:
        raise ValueError(
            f"cannot cut {n} blocks into {n_stages} stages — at most one "
            f"stage per block"
        )
    prefix = [0]
    for b in block_bytes:
        prefix.append(prefix[-1] + int(b))

    def span(i: int, j: int) -> int:  # bytes of blocks [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # cost[k][j]: minimal max-share cutting the first j blocks into k stages.
    cost = [[INF] * (n + 1) for _ in range(n_stages + 1)]
    cut = [[0] * (n + 1) for _ in range(n_stages + 1)]
    cost[0][0] = 0
    for k in range(1, n_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(cost[k - 1][i], span(i, j))
                if c < cost[k][j]:
                    cost[k][j], cut[k][j] = c, i
    bounds = [n]
    for k in range(n_stages, 0, -1):
        bounds.append(cut[k][bounds[-1]])
    bounds.reverse()  # [0, c1, ..., n]
    out: List[int] = []
    for s in range(n_stages):
        out.extend([s] * (bounds[s + 1] - bounds[s]))
    return out


def split_tree_by_stage(
    rules: Sequence[StageRule], tree: PyTree, n_stages: int, prefix: str
) -> List[PyTree]:
    """Split a nested-dict pytree into ``n_stages`` same-shape subtrees by
    leaf-name stage assignment — stage s's tree keeps exactly its leaves
    (empty dicts pruned).  The inverse of :func:`merge_stage_trees`; both
    are pure host-side dict surgery, so the canonical checkpoint layout
    round-trips through them byte-identically (tests pin it)."""

    def place(out, path_keys, leaf):
        node = out
        for k in path_keys[:-1]:
            node = node.setdefault(k, {})
        node[path_keys[-1]] = leaf

    outs: List[dict] = [{} for _ in range(n_stages)]
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        name = leaf_name(prefix, path)
        stage = match_stage_rules(rules, name)
        if not 0 <= stage < n_stages:
            raise ValueError(
                f"stage rule for {name!r} assigns stage {stage}, outside "
                f"[0, {n_stages})"
            )
        place(outs[stage], [_key_str(k) for k in path], leaf)
    return outs


def merge_stage_trees(stage_trees: Sequence[PyTree]) -> PyTree:
    """Deep-merge per-stage nested-dict subtrees back into one tree —
    the canonical gathered layout checkpoints store.  Key collisions
    raise: stages own disjoint blocks by construction, so a collision
    means two stage tables disagree about ownership."""

    def merge_into(dst: dict, src: dict, path: str):
        for k, v in src.items():
            here = f"{path}/{k}" if path else str(k)
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                merge_into(dst[k], v, here)
            elif k in dst:
                raise ValueError(
                    f"stage trees collide at {here!r} — stages must own "
                    f"disjoint blocks"
                )
            else:
                dst[k] = v

    out: dict = {}
    for t in stage_trees:
        merge_into(out, t, "")
    return out


# ---------------------------------------------------------------------------
# checkpoint shard / gather fns


def make_shard_and_gather_fns(
    decisions: PyTree, n_shards: int, mode: str
) -> Tuple[PyTree, PyTree]:
    """Per-leaf ``(shard_fns, gather_fns)`` callables derived from one
    decision tree (the SNIPPETS.md [2] pattern): ``shard_fn(full_leaf)``
    produces the run-layout value a checkpoint restore places,
    ``gather_fn(run_leaf)`` restores the canonical full leaf a
    checkpoint stores.  In ``mode='chunk'``, auto-sharded decisions
    chunk/unchunk the ``[N, K]`` view; in ``mode='leaf'`` (and for every
    replicated decision) the fns are the identity — those layout changes
    are placement-only, owned by the sharding trees.
    ``StateLayout.place``/``canonical`` jit these, so checkpoints stay
    layout-independent from the same table that places the live state."""
    from ddlpc_tpu.parallel.shard_update import chunk_leaf, unchunk_leaf

    if mode not in ("chunk", "leaf"):
        raise ValueError(f"unknown partition mode {mode!r}")
    chunked = mode == "chunk"

    def shard_fn(d: Decision):
        if chunked and d.reason == REASON_AUTO:
            return lambda x, n=n_shards: chunk_leaf(x, n)
        return lambda x: x

    def gather_fn(d: Decision):
        if chunked and d.reason == REASON_AUTO:
            return lambda x, shape=d.shape: unchunk_leaf(x, shape)
        return lambda x: x

    return jax.tree.map(shard_fn, decisions), jax.tree.map(gather_fn, decisions)
