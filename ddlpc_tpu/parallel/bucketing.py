"""Size-targeted gradient buckets for comm/compute overlap.

The DDP trick (PAPERS.md: PyTorch DDP, Horovod): instead of one whole-tree
gradient sync at the end of backward, partition the leaves into buckets of
roughly ``bucket_mb`` MiB and issue each bucket's collective as its grads
become available, so backward compute of earlier layers overlaps the sync
of later ones.  The source paper approximated the same hiding with
50-microbatch accumulation; buckets hide the wire *within* one sync.

Assignment is a pure function of the leaf byte sizes in flatten order —
greedy: walk the leaves, open a new bucket whenever adding the next leaf
would exceed the target and the current bucket is non-empty.  Purity is
the load-bearing property: the replicated, ZeRO-1 and GSPMD step builders
all derive their buckets from the same parameter tree, so every layout
sees the *same* partition (the program auditor's collective census counts
the buckets per layout and pins that they agree), and replicated↔sharded
bit-identity (docs/SHARDING.md) is preserved bucket-for-bucket.

Deliberately dependency-free (stdlib only): the assignment must be
computable by observability code (``obs/comm.py`` byte accounting) and
tooling without touching jax.
"""

from __future__ import annotations

from typing import List, Sequence

MIB = float(1 << 20)


def assign_buckets(leaf_bytes: Sequence[int], bucket_mb: float) -> List[int]:
    """Bucket index per leaf (flatten order) for a greedy ``bucket_mb`` MiB
    target.  ``bucket_mb <= 0`` means "no bucketing": every leaf lands in
    bucket 0 and the sync degenerates to today's single whole-tree
    collective.  A leaf larger than the target gets a bucket of its own
    (never split); the last bucket is whatever remains (usually under
    target).  Indices are contiguous starting at 0."""
    if bucket_mb <= 0 or not leaf_bytes:
        return [0] * len(leaf_bytes)
    target = bucket_mb * MIB
    out: List[int] = []
    bucket = 0
    acc = 0.0
    for nbytes in leaf_bytes:
        if acc > 0 and acc + nbytes > target:
            bucket += 1
            acc = 0.0
        out.append(bucket)
        acc += nbytes
    return out


def bucket_index_groups(
    leaf_bytes: Sequence[int], bucket_mb: float
) -> List[List[int]]:
    """Leaf indices grouped per bucket, in bucket order — the iteration
    order every step builder uses, so bucket ``b`` means the same leaves
    in every layout."""
    assignment = assign_buckets(leaf_bytes, bucket_mb)
    n_buckets = (max(assignment) + 1) if assignment else 1
    groups: List[List[int]] = [[] for _ in range(n_buckets)]
    for i, b in enumerate(assignment):
        groups[b].append(i)
    return groups


def bucket_count(leaf_bytes: Sequence[int], bucket_mb: float) -> int:
    """How many buckets ``assign_buckets`` produces — the ``B`` in the
    auditor's fence/byte closed forms and ``obs/comm.py``'s scale-byte
    accounting."""
    assignment = assign_buckets(leaf_bytes, bucket_mb)
    return (max(assignment) + 1) if assignment else 1
