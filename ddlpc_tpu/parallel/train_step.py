"""SPMD train/eval steps: shard_map over the device mesh.

This module is the TPU-native equivalent of the reference's two training
drivers (server кластер.py:690-790, worker :792-895) collapsed into one SPMD
program:

- micro-batch gradient accumulation over ``sync_period`` steps is a
  ``lax.scan`` (reference: Python loop + loss.backward() accumulating into
  param.grad, кластер.py:750-759);
- gradient synchronization is one fused all-reduce inside the compiled step
  (reference: pickle → mgzip → TCP star round trip, кластер.py:255-557) with
  the optional lossy codec applied at the same points (see grad_sync.py);
- the optimizer step runs identically on every replica on bit-identical
  gradients (reference guarantees this by re-broadcasting the quantized
  average and self-applying it, кластер.py:402-438).

Everything is a pure function of (state, batch); the whole step —
A micro-batches of forward/backward, the all-reduce, the codec, the Adam
update — compiles to a single XLA executable with no host round trips.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import CompressionConfig, ExperimentConfig
from ddlpc_tpu.models.layers import group_labels
from ddlpc_tpu.utils.compat import shard_map
from ddlpc_tpu.ops.losses import nll_correct_valid, softmax_cross_entropy_sum
from ddlpc_tpu.ops.metrics import confusion_from_logits
from ddlpc_tpu.parallel.grad_sync import sync_gradients, sync_gradients_scatter
from ddlpc_tpu.parallel import shard_update as zero

PyTree = Any


def _rounding_rng(
    compression: CompressionConfig, seed: int, step: jax.Array
) -> Optional[jax.Array]:
    """Stochastic-rounding key: a pure function of (experiment seed,
    replicated step counter), so every replica derives the same key
    (bit-identical rounding decisions), resumed runs replay the same noise,
    and different seeds draw different rounding noise (seed-sensitivity
    studies need the noise to vary with the seed).  Shared by both step
    builders so their key schedules cannot diverge."""
    if compression.rounding != "stochastic":
        return None
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.key(0x5EED), seed), step
    )


class TrainState(struct.PyTreeNode):
    """Replicated training state.

    The reference distributes this by pickling the live ``[network,
    optimizer, criterion]`` CUDA object graph over TCP at startup
    (кластер.py:560-565); here it is a pytree that the mesh keeps replicated.
    """

    step: jax.Array
    params: PyTree
    batch_stats: PyTree
    opt_state: PyTree


def create_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    input_shape: Tuple[int, ...],
) -> TrainState:
    """Initialize parameters/optimizer on host. input_shape: [N, H, W, C]."""
    variables = model.init(rng, jnp.zeros(input_shape, jnp.float32), train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )


def _loss_and_metrics(
    model: nn.Module,
    params: PyTree,
    batch_stats: PyTree,
    images: jax.Array,
    labels: jax.Array,
    train: bool,
):
    variables = {"params": params, "batch_stats": batch_stats}
    if train:
        logits, updates = model.apply(
            variables, images, train=True, mutable=["batch_stats"]
        )
        new_stats = updates["batch_stats"]
    else:
        logits = model.apply(variables, images, train=False)
        new_stats = batch_stats
    loss, acc = loss_from_logits(model, logits, labels, train)
    return loss, (new_stats, acc)


def loss_from_logits(
    model: nn.Module, logits: jax.Array, labels: jax.Array, train: bool
) -> Tuple[jax.Array, jax.Array]:
    """The loss/accuracy tail of :func:`_loss_and_metrics`, factored out
    so the pipeline's last-stage segment (parallel/pipeline.py) applies
    byte-identical loss math to logits produced by staged execution —
    one owner for the grouped-head regrouping and the void-pixel mean."""
    # train_head_layout='grouped': the model returned pre-d2s phase-major
    # logits [..., H/r, W/r, r²·C] (models/layers.py:group_labels).  Group
    # the labels the same way and run the SAME loss/metric functions on the
    # [..., r², C] view — identical math (same multiset of (logit row,
    # label) pairs), no full-res tensor or d2s transpose in the train graph.
    if logits.shape[-3:-1] != labels.shape[-2:]:
        # Only regroup when the model DECLARED the grouped layout — a model
        # bug producing wrong-shaped logits whose dims happen to divide the
        # labels must error, not silently train on scrambled pairings.
        declared = getattr(model, "train_head_layout", "fullres")
        if not (train and declared == "grouped"):
            raise ValueError(
                f"logits spatial shape {logits.shape[-3:-1]} != labels "
                f"{labels.shape[-2:]} but the model declares "
                f"train_head_layout={declared!r} (train={train}) — refusing "
                "to reinterpret as grouped logits"
            )
        r = labels.shape[-2] // logits.shape[-3]
        if (labels.shape[-2] != r * logits.shape[-3]
                or labels.shape[-1] != r * logits.shape[-2]):
            raise ValueError(
                f"grouped logits {logits.shape} are not an integer r×r "
                f"regrouping of labels {labels.shape}"
            )
        labels = group_labels(labels, r)
        logits = logits.reshape(*logits.shape[:-1], r * r, -1)
    # -1 marks void/ignored pixels (e.g. Cityscapes' unlabeled classes,
    # scripts/prepare_cityscapes.py); they contribute neither loss nor
    # accuracy.  Datasets without voids have no -1 labels, so this is a
    # no-op for them.  The mean is per-micro-batch over ITS valid pixels
    # (then gradients average equally across micro-batches/replicas) —
    # deliberately the torch CrossEntropyLoss(reduction='mean') + DDP
    # semantics the reference inherits, not a globally pixel-weighted mean;
    # the eval path (softmax_cross_entropy_sum) is globally weighted.
    # Loss and accuracy come from ONE fused pass over the logits
    # (ops/losses.py:nll_correct_valid) — computing them separately cost
    # ~90 ms/step in fp32 materializations and layout copies of the
    # largest tensor in the step (docs/head_bench/trace_plain_grouped.json).
    nll, correct, valid = nll_correct_valid(logits, labels, ignore_index=-1)
    # Deep-supervision stacks ([J, ...] logits with labels broadcast over
    # J): broadcasting valid to nll's shape makes the denominator count
    # head×pixel terms, so the loss is the MEAN of per-head losses (the
    # documented U-Net++ semantics) and accuracy stays in [0, 1].  The
    # previous sum/valid.sum() form counted pixels once — J× the per-head
    # mean and >1 accuracies (review find, round 4; Adam's update is
    # invariant to the loss scale, so committed r3 U-Net++ curves remain
    # valid trajectories — only the reported loss/acc change).
    valid = jnp.broadcast_to(valid, nll.shape)
    denom = jnp.maximum(valid.sum(), 1.0)
    loss = (nll * valid).sum() / denom
    acc = (correct * valid).sum() / denom
    return loss, acc


def _accumulate_grads(
    model: nn.Module,
    state: "TrainState",
    images: jax.Array,
    labels: jax.Array,
    remat: bool = False,
):
    """Scan ``A`` micro-batches accumulating fp32 grads (the reference's
    loss.backward() accumulation loop, кластер.py:750-759).  Shared by the
    shard_map and GSPMD step builders so their semantics cannot diverge.
    Returns (mean grads, new batch_stats, losses [A], accs [A]).

    ``remat=True`` wraps each micro-batch's forward in ``jax.checkpoint``:
    no activations are stored between forward and backward — the backward
    pass recomputes the forward — trading ~1/3 more FLOPs for the peak-HBM
    headroom to run larger micro-batches (TrainConfig.remat).
    """

    def loss_fn(p, stats, x, y):
        return _loss_and_metrics(model, p, stats, x, y, train=True)

    if remat:
        loss_fn = jax.checkpoint(loss_fn)

    def micro(carry, xy):
        grads_acc, stats = carry
        x, y = xy
        (loss, (stats, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, stats, x, y)
        grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
        return (grads_acc, stats), (loss, acc)

    zeros = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), state.params)
    (grads, batch_stats), (losses, accs) = lax.scan(
        micro, (zeros, state.batch_stats), (images, labels)
    )
    grads = jax.tree.map(lambda g: g / images.shape[0], grads)
    return grads, batch_stats, losses, accs


def _fenced_update(
    tx: optax.GradientTransformation,
    grads: PyTree,
    opt_state: PyTree,
    params: PyTree,
) -> Tuple[PyTree, PyTree]:
    """tx.update + apply_updates inside ``lax.optimization_barrier`` fences.

    The barriers pin the optimizer arithmetic into an isolated fusion
    region: without them XLA fuses the elementwise Adam chain into its
    *surrounding* ops — the all-reduce consumer in the replicated step, the
    reduce-scatter/all-gather pair in the sharded one — and the two
    programs then contract mul+add into FMA differently on small leaves,
    producing 1-ulp drift between layouts (observed on the CPU backend:
    identical mean gradients and moments in, updates differing by 1 ulp on
    bias/BatchNorm leaves from step 2 on).  With the fence the update
    subprogram is bit-identical across layouts — the property the
    shard-vs-replicated identity tests and cross-layout checkpoint
    restores rely on.  Perf cost: none measurable (the update is a few
    fused elementwise loops either side of the fence).
    """
    grads, opt_state, params = lax.optimization_barrier(
        (grads, opt_state, params)
    )
    updates, new_opt = tx.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    return lax.optimization_barrier((new_params, new_opt))


def _psum_sq_norm(tree: PyTree, axis_name: str) -> jax.Array:
    """Global gradient norm from per-replica partial sums of squares —
    under the sharded update each replica only holds 1/N of the mean
    gradient, so the squared partials are psum'd before the sqrt to keep
    the logged ``grad_norm`` comparable across all step variants."""
    leaves = jax.tree_util.tree_leaves(tree)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(lax.psum(sq, axis_name))


def _apply_update_sharded(
    tx: optax.GradientTransformation,
    params: PyTree,
    opt_state: PyTree,
    grads: PyTree,
    data_axis: str,
    axis_size: int,
    compression: CompressionConfig,
    key,
):
    """The ZeRO-2 weight-update path, called inside shard_map with LOCAL
    values: full per-replica ``grads``/``params``, this replica's ``[1, K]``
    chunks of the optimizer moments in ``opt_state``.  The gradient sync
    is a reduce-scatter — the optimizer-boundary gradient only ever
    materializes as this replica's shard (1/N of the tree per device),
    which is what makes this zero2: zero1's full-mean all-reduce IS this
    reduce-scatter plus an all-gather of gradients nobody needs
    (``_apply_update_zero1``).  Returns the fresh full params
    (all-gathered), the updated local moment chunks, and the psum'd grad
    norm of the post-codec mean.  Shared by the train step and the
    update-only bench program so their semantics cannot diverge."""
    grad_shards = sync_gradients_scatter(
        grads, data_axis, compression, axis_size=axis_size, key=key
    )
    param_shards = jax.tree.map(
        lambda p: zero.local_chunk(p, axis_size, data_axis), params
    )
    new_param_shards, new_opt = _fenced_update(
        tx, grad_shards, opt_state, param_shards
    )
    new_params = jax.tree.map(
        lambda sh, p: zero.unchunk_leaf(
            lax.all_gather(sh, data_axis, axis=0, tiled=True), p.shape
        ),
        new_param_shards,
        params,
    )
    return new_params, new_opt, _psum_sq_norm(grad_shards, data_axis)


def _apply_update_zero1(
    tx: optax.GradientTransformation,
    params: PyTree,
    opt_state: PyTree,
    grads: PyTree,
    data_axis: str,
    axis_size: int,
    compression: CompressionConfig,
    key,
):
    """The TRUE ZeRO-1 weight-update path (sharded moments, full-mean
    gradient sync): the all-reduce is the unmodified ``sync_gradients``
    — every codec and transport composes, the ring and the pallas mean
    stage included, because the codec sees the whole mean — then each
    replica slices its ``[1, K]`` row of the mean and of the params,
    runs the fenced update on the chunks, and all-gathers fresh params.

    DECLARED DEVIATION (test-pinned): zero1 trajectories match
    replicated/zero2 to within FMA-contraction ulps, not byte-for-byte.
    The update's *inputs* are bit-identical — the sliced mean equals the
    scatter path's shards element-for-element (``psum`` ≡ ``psum_scatter``
    per element is test-pinned, and the scatter codec quantizes shards
    with the global scale and the sliced full-shape noise field precisely
    so its shards equal slices of the full quantized mean; both pins in
    tests/test_shard_update.py) — but the chunk *slice* feeds the update
    through fusable ops, the backend fuses it into the Adam kernel
    (``lax.optimization_barrier`` does not block loop fusion on the CPU
    backend — verified in the optimized HLO), and LLVM then contracts
    mul+add into FMA differently than in the replicated/zero2 kernels,
    whose update inputs are jit-boundary or collective outputs: ≤1-ulp
    drift per step on small leaves.  zero2/zero3 keep the byte-for-byte
    bar; zero1 exists for the combinations the scatter path refuses
    (``resolve_shard_update``: ring transport, pallas mean stage — codecs
    whose *declared* loss dwarfs an update ulp) and as the honest A/B
    baseline for the zero2-≤-zero1 perf claim (``bench.py --update-ab``).
    Wire: zero1 moves 3·P elements per step (2·P all-reduce + P params
    all-gather) where zero2 moves 2·P — zero2 literally stops
    all-gathering what the reduce-scatter just produced."""
    grads = sync_gradients(
        grads, data_axis, compression, axis_size=axis_size, key=key
    )
    grad_norm = optax.global_norm(grads)
    grad_shards = jax.tree.map(
        lambda g: zero.local_chunk(g, axis_size, data_axis), grads
    )
    param_shards = jax.tree.map(
        lambda p: zero.local_chunk(p, axis_size, data_axis), params
    )
    new_param_shards, new_opt = _fenced_update(
        tx, grad_shards, opt_state, param_shards
    )
    new_params = jax.tree.map(
        lambda sh, p: zero.unchunk_leaf(
            lax.all_gather(sh, data_axis, axis=0, tiled=True), p.shape
        ),
        new_param_shards,
        params,
    )
    return new_params, new_opt, grad_norm


def _zero_state_specs(
    state: TrainState,
    tx: optax.GradientTransformation,
    data_axis: str,
    level: str,
) -> TrainState:
    """shard_map partition specs for the chunked run layouts: stats/step
    replicated, chunked opt-state moments split over ``data_axis``;
    params replicated for zero1/zero2 and chunked (``P(data)`` on the
    ``[N, K]`` view) for zero3.  Built at trace time from the state's
    avals via the partition-rule tables (shard_update.py) — for zero3
    the state's params are already chunk-shaped, which the name-matched
    rules place identically (the opt template derived from chunked
    params has the same treedef and moment names)."""
    opt_specs = zero.opt_partition_specs(tx, state.params, level, data_axis)
    param_spec = P(data_axis) if level == "zero3" else P()
    return state.replace(
        step=P(),
        params=jax.tree.map(lambda _: param_spec, state.params),
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=opt_specs,
    )


def make_train_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    compression: CompressionConfig,
    data_axis: str = "data",
    donate_state: bool = True,
    remat: bool = False,
    seed: int = 0,
    shard_update: bool = False,
    param_avals: Optional[PyTree] = None,
) -> Callable[[TrainState, jax.Array, jax.Array], Tuple[TrainState, dict]]:
    """Build the jitted SPMD train step.

    Inputs per call:
      images [A, B, H, W, C], labels [A, B, H, W] — A = sync_period
    (micro-batches accumulated between optimizer steps, reference
    ``frequency_sending_gradients`` кластер.py:685), B = *global* micro-batch,
    sharded over the data axis.
    Returns (new_state, metrics) with metrics averaged over A and the mesh.

    ``shard_update`` selects the ZeRO level of the weight update
    (shard_update.py, docs/SHARDING.md; the historical bool maps
    ``True`` → ``'zero2'``, the program this repo has always called the
    sharded update):

    - ``'zero1'``: full-mean all-reduce, then each replica updates its
      1/N chunk of params + moments and all-gathers the params.
    - ``'zero2'``: the gradient sync IS a reduce-scatter; the update
      runs on the shards; one all-gather publishes the params.
    - ``'zero3'``: params also persist as ``[N, K]`` chunks; the step
      starts by all-gathering them per leaf for the forward/backward
      (they are step temporaries, freed after use) and the fresh chunks
      are NOT gathered at step end.  Requires ``param_avals`` — the
      canonical parameter shapes the chunks restore to.

    The state must be in the matching run layout
    (``shard_update.StateLayout``).  zero2 and zero3 are bit-identical
    to the replicated update for every supported codec mode
    (test-pinned); zero1 matches to within FMA-contraction ulps — a
    declared, test-pinned deviation (see ``_apply_update_zero1``).  On a
    singleton data mesh all levels fall back to the replicated program
    (sharding into one shard IS replication).

    Precondition on ``tx`` (uncheckable — optax chains are opaque): no
    stage may couple elements across the tree, e.g. ``clip_by_global_norm``
    — under every chunked level each replica's ``tx.update`` sees only its
    1/N chunk, so a global-norm clip would use the shard's partial norm
    (wrong threshold, replica-divergent params).  The config path enforces
    this via ``resolve_shard_update(grad_clip_norm=...)``; direct callers
    own it.
    """
    for name, size in mesh.shape.items():
        if name != data_axis and size > 1:
            raise ValueError(
                f"mesh axis {name!r} (size {size}) is not consumed by the "
                f"shard_map train step — use make_train_step_gspmd for "
                f"data×space meshes (the Trainer selects it automatically) "
                f"or parallel/pipeline.make_pipeline_train_step for meshes "
                f"with a pipe axis"
            )
    axis_size = mesh.shape[data_axis]
    level = zero.normalize_shard_update(shard_update)
    if axis_size <= 1:
        level = "off"
    if level in ("zero2", "zero3"):
        from ddlpc_tpu.parallel.grad_sync import validate_scatter_compression

        validate_scatter_compression(compression)
    if level == "zero3" and param_avals is None:
        raise ValueError(
            "make_train_step(shard_update='zero3') requires param_avals — "
            "the canonical parameter shapes the chunked leaves restore to "
            "(StateLayout.param_avals)"
        )

    def shard_body(state: TrainState, images: jax.Array, labels: jax.Array):
        # Inside shard_map: images [A, B_local, H, W, C].
        if level == "zero3":
            # Gather-on-demand: the persisted params are this replica's
            # [1, K] chunks; all-gather each leaf back to its canonical
            # shape for the forward/backward.  The gathered tree is a
            # step temporary — XLA frees it after the backward — so the
            # full model never persists in HBM between steps.
            full_params = jax.tree.map(
                lambda ch, av: zero.unchunk_leaf(
                    lax.all_gather(ch, data_axis, axis=0, tiled=True),
                    av.shape,
                ),
                state.params,
                param_avals,
            )
            fwd_state = state.replace(params=full_params)
        else:
            fwd_state = state
        grads, batch_stats, losses, accs = _accumulate_grads(
            model, fwd_state, images, labels, remat=remat
        )
        # Keep BatchNorm running stats replica-identical at every sync point:
        # with per-batch sync-BN (norm_axis_name set) this pmean is a no-op;
        # without it, it averages the per-replica running stats — either way
        # the returned state is genuinely replicated, unlike the reference,
        # which never re-syncs BN stats after init (SURVEY §3.1).
        batch_stats = jax.tree.map(
            lambda x: lax.pmean(x, data_axis), batch_stats
        )
        # The one (logical) collective of the step — replaces reference
        # L0–L4.  Sharded: reduce-scatter + all-gather, the same wire bytes
        # split around a 1/N-sized update.
        rng = _rounding_rng(compression, seed, state.step)
        if level == "zero2":
            params, opt_state, grad_norm = _apply_update_sharded(
                tx, state.params, state.opt_state, grads,
                data_axis, axis_size, compression, rng,
            )
        elif level == "zero1":
            params, opt_state, grad_norm = _apply_update_zero1(
                tx, state.params, state.opt_state, grads,
                data_axis, axis_size, compression, rng,
            )
        elif level == "zero3":
            # Same wire as zero2's scatter, but the fresh param chunks
            # are the NEW persisted state — no publish all-gather; the
            # next step's gather-on-demand replaces it.
            grad_shards = sync_gradients_scatter(
                grads, data_axis, compression, axis_size=axis_size, key=rng
            )
            params, opt_state = _fenced_update(
                tx, grad_shards, state.opt_state, state.params
            )
            grad_norm = _psum_sq_norm(grad_shards, data_axis)
        else:
            grads = sync_gradients(
                grads, data_axis, compression, axis_size=axis_size, key=rng
            )
            params, opt_state = _fenced_update(
                tx, grads, state.opt_state, state.params
            )
            grad_norm = optax.global_norm(grads)
        metrics = {
            "loss": lax.pmean(losses.mean(), data_axis),
            "pixel_acc": lax.pmean(accs.mean(), data_axis),
            "grad_norm": grad_norm,
        }
        new_state = TrainState(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        return new_state, metrics

    donate = (0,) if donate_state else ()
    if level == "off":
        sharded = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), P(None, data_axis), P(None, data_axis)),
            out_specs=(P(), P()),
            check=False,
        )
        return jax.jit(sharded, donate_argnums=donate)

    def stepper(state: TrainState, images: jax.Array, labels: jax.Array):
        # Specs depend on the state's (chunked) structure — build them at
        # trace time from the avals; shard_map composes under jit.
        specs = _zero_state_specs(state, tx, data_axis, level)
        sharded = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(specs, P(None, data_axis), P(None, data_axis)),
            out_specs=(specs, P()),
            check=False,
        )
        return sharded(state, images, labels)

    return jax.jit(stepper, donate_argnums=donate)


def make_train_step_gspmd(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    compression: CompressionConfig,
    data_axis: str = "data",
    space_axis: Optional[str] = "space",
    donate_state: bool = True,
    remat: bool = False,
    seed: int = 0,
    shard_update: bool = False,
) -> Callable[[TrainState, jax.Array, jax.Array], Tuple[TrainState, dict]]:
    """GSPMD train step: batch sharded over ``data`` AND H over ``space``.
    ``shard_update`` takes the same levels as :func:`make_train_step`
    (bool ``True`` → ``'zero2'``), expressed the GSPMD way — sharding
    constraints instead of hand-written collectives; the Trainer's
    ``StateLayout`` modes ``gspmd``/``gspmd_zero2``/``gspmd_zero3`` are
    the matching placements.

    Where the shard_map path writes the collectives by hand, here the
    program is expressed over *global* arrays and XLA's SPMD partitioner
    inserts everything: the gradient all-reduce over ``data``, and — the
    point of this path — per-conv halo exchanges over ``space`` for
    H-sharded tiles (see parallel/halo.py for the hand-written equivalent).
    This is how the framework trains tiles too large for one chip's HBM,
    the spatial analog of sequence/context parallelism.

    Differences vs the shard_map path, by construction:
    - BatchNorm must be built WITHOUT ``norm_axis_name``: batch statistics
      are computed over the logical global batch, which the partitioner
      turns into exact cross-replica sync-BN on its own.
    - The codec's ``quantize_local`` stage (per-replica quantization before
      the reduce, кластер.py:450-496) has no meaning here — there is no
      per-replica gradient in the program; only ``quantize_mean``
      (кластер.py:328-396) applies.  The shard_map path remains the
      reference-parity codec path.

    Levels, the GSPMD spelling (the mechanism of arxiv 2004.13336 — the
    XLA partitioner materializes the collectives around the elementwise
    update on its own):

    - ``'zero1'``: optimizer moments stay parameter-shaped but are
      *partitioned* over ``data_axis`` (``partition.even_shard_spec``
      picks the dimension), pinned by sharding constraints on both the
      incoming state (Trainer placement) and the step's output.
    - ``'zero2'``: additionally pins the post-codec mean gradient to the
      same rule-derived shardings, so the partitioner is told the
      optimizer-boundary gradient is sharded (it emits a reduce-scatter
      into the update rather than keeping a replicated mean alive).
    - ``'zero3'``: params persist partitioned at the state boundary too
      (rule-engine specs; uneven leaves stay replicated-by-rule) — the
      partitioner gathers them per consuming op in the forward/backward,
      the true gather-on-demand form.

    The codec still sees the full *logical* mean gradient inside the
    partitioned program, so no codec mode is restricted on this path.
    """

    if compression.mode != "none" and not compression.quantize_mean:
        raise ValueError(
            "the GSPMD step cannot represent quantize_local-only compression "
            "(there is no per-replica gradient in the program): set "
            "compression.quantize_mean=True, or mode='none', or use a pure "
            "data mesh for reference-parity codec semantics"
        )
    if compression.transport == "ring" and compression.mode != "none":
        raise ValueError(
            "transport='ring' requires explicit per-replica collectives — "
            "use the shard_map step (pure data mesh); the GSPMD partitioner "
            "owns the collectives in this path"
        )
    if compression.mode != "none" and compression.quantize_local:
        # Refuse rather than silently drop a configured loss point: a config
        # recording quantize_local=True would claim codec semantics the
        # executed program does not have.  The config artifact must match
        # what runs.
        raise ValueError(
            "the GSPMD step cannot apply quantize_local (no per-replica "
            "gradient exists in the program — only the averaged gradient is "
            "representable): set compression.quantize_local=False to record "
            "the semantics that actually execute, or use a pure data mesh "
            "(shard_map step) for reference-parity two-point codec semantics"
        )

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(None, data_axis, space_axis))
    n_data = mesh.shape[data_axis]
    level = zero.normalize_shard_update(shard_update)
    if n_data <= 1:
        level = "off"
    layout = zero.GSPMD_LAYOUT_FOR_LEVEL.get(level)

    def _constrain_by_decisions(tree: PyTree, decisions: PyTree) -> PyTree:
        """Pin each rule-sharded leaf to its decision's sharding; leaves
        the rules keep replicated get no constraint (the partitioner may
        place them freely — the state boundary pins what persists)."""
        return jax.tree.map(
            lambda l, d: (
                lax.with_sharding_constraint(l, NamedSharding(mesh, d.spec))
                if d.sharded
                else l
            ),
            tree,
            decisions,
        )

    def step_fn(state: TrainState, images: jax.Array, labels: jax.Array):
        grads, batch_stats, losses, accs = _accumulate_grads(
            model, state, images, labels, remat=remat
        )
        if compression.mode != "none":
            from ddlpc_tpu.parallel.grad_sync import (
                apply_codec_fenced_bucketed,
                resolve_codec_backend,
            )

            rng = _rounding_rng(compression, seed, state.step)
            # Bucketed spelling so the GSPMD codec loss (per-bucket scales
            # and keys) matches the shard_map layouts bucket-for-bucket;
            # bucket_mb=0 degenerates to the single fenced whole-tree stage.
            grads = apply_codec_fenced_bucketed(
                resolve_codec_backend(compression), grads, compression, key=rng
            )
        if level in ("zero2", "zero3"):
            # ZeRO-2 the GSPMD way: pin the post-codec mean gradient to the
            # rule-derived shardings, telling the partitioner the
            # optimizer-boundary gradient is sharded — it materializes a
            # reduce-scatter into the update instead of keeping a
            # replicated mean alive between codec and update.  Values are
            # untouched (placement only); the codec above already ran on
            # the full logical mean, so bit-identity with the other
            # layouts is unchanged.
            grads = _constrain_by_decisions(
                grads,
                zero.param_decisions(
                    grads, layout, n_data, data_axis, prefix="grads"
                ),
            )
        params, opt_state = _fenced_update(
            tx, grads, state.opt_state, state.params
        )
        if level != "off":
            # With the output state's shardings unconstrained at the jit
            # boundary, pin them here: stats replicated, params replicated
            # (zero1/zero2 — the next forward and eval/predict need them
            # whole) or rule-sharded (zero3 — they persist partitioned and
            # the partitioner gathers per consuming op next step), fresh
            # moments in the ZeRO layout so the partitioner keeps them
            # sharded across steps (and therefore shards the elementwise
            # update math that produces them) instead of replicating the
            # output.
            batch_stats = lax.with_sharding_constraint(batch_stats, repl)
            if level == "zero3":
                params = _constrain_by_decisions(
                    params,
                    zero.param_decisions(params, layout, n_data, data_axis),
                )
            else:
                params = lax.with_sharding_constraint(params, repl)
            opt_state = _constrain_by_decisions(
                opt_state,
                zero.opt_decisions(tx, state.params, layout, n_data, data_axis),
            )
        metrics = {
            "loss": losses.mean(),
            "pixel_acc": accs.mean(),
            "grad_norm": optax.global_norm(grads),
        }
        new_state = TrainState(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        return new_state, metrics

    if level == "off":
        return jax.jit(
            step_fn,
            in_shardings=(repl, batch_sh, batch_sh),
            out_shardings=(repl, repl),
            donate_argnums=(0,) if donate_state else (),
        )

    # Sharded state: the state's sharding tree mixes replicated and
    # P(data)-partitioned leaves, and its structure is unknown until the
    # first state arrives — build the jit lazily from that state's tree,
    # with EXPLICIT and identical in/out shardings.  (Leaving the state
    # boundary unspecified makes jit infer the donation aliasing across
    # mismatched layouts, which XLA rejects at dispatch: "aliased input
    # and output to have the same size".)
    cache: dict = {}

    def build(state: TrainState):
        """The inner jit for a state of this tree (avals suffice — the
        program auditor lowers it on ShapeDtypeStructs without running;
        ``stepper`` caches it for the real training loop)."""
        opt_sh = zero.opt_shardings(
            tx, state.params, layout, mesh, data_axis
        )
        if level == "zero3":
            param_sh = jax.tree.map(
                lambda d: NamedSharding(mesh, d.spec) if d.sharded else repl,
                zero.param_decisions(state.params, layout, n_data, data_axis),
            )
        else:
            param_sh = jax.tree.map(lambda _: repl, state.params)
        state_sh = state.replace(
            step=repl,
            params=param_sh,
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=opt_sh,
        )
        return jax.jit(
            step_fn,
            in_shardings=(state_sh, batch_sh, batch_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=(0,) if donate_state else (),
        )

    def stepper(state: TrainState, images: jax.Array, labels: jax.Array):
        fn = cache.get("fn")
        if fn is None:
            fn = cache["fn"] = build(state)
        return fn(state, images, labels)

    stepper.build_for = build
    return stepper


def make_update_step(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    compression: CompressionConfig,
    data_axis: str = "data",
    shard_update: bool = False,
    seed: int = 0,
) -> Callable[[PyTree, PyTree, PyTree], Tuple[PyTree, PyTree]]:
    """Update-ONLY SPMD program: (params, opt_state, grads) → (params,
    opt_state) — the gradient sync + optimizer step with no forward/
    backward, for benchmarking the weight-update path in isolation
    (``bench.py --update-ab``, the ``update_ms_per_step`` contract line).
    ``grads`` is the per-replica accumulated gradient tree (replicated
    input); ``shard_update`` takes the same levels as
    :func:`make_train_step` (bool ``True`` → ``'zero2'``), and
    ``params``/``opt_state`` must be in the matching layout: chunked
    moments for every chunked level, chunked params too for ``'zero3'``
    (whose program is the zero2 wire with no publish all-gather — fresh
    chunks ARE the output, so this arm prices exactly the persisted-
    sharded-params update).  Stochastic rounding uses the shared key
    schedule pinned at step 0 (no step counter flows through this
    program): every call rounds with the same noise — right for timing
    the codec's real threefry cost, wrong for training, which the fused
    steps own.  Same ``tx`` precondition as ``make_train_step``: no
    cross-tree coupling (e.g. ``clip_by_global_norm``) when sharded.
    """
    axis_size = mesh.shape[data_axis]
    level = zero.normalize_shard_update(shard_update)
    if axis_size <= 1:
        level = "off"
    if level in ("zero2", "zero3"):
        from ddlpc_tpu.parallel.grad_sync import validate_scatter_compression

        validate_scatter_compression(compression)

    def body(params: PyTree, opt_state: PyTree, grads: PyTree):
        rng = _rounding_rng(compression, seed, 0)
        if level == "zero2":
            params, opt_state, _ = _apply_update_sharded(
                tx, params, opt_state, grads,
                data_axis, axis_size, compression, rng,
            )
        elif level == "zero1":
            params, opt_state, _ = _apply_update_zero1(
                tx, params, opt_state, grads,
                data_axis, axis_size, compression, rng,
            )
        elif level == "zero3":
            grad_shards = sync_gradients_scatter(
                grads, data_axis, compression, axis_size=axis_size, key=rng
            )
            params, opt_state = _fenced_update(
                tx, grad_shards, opt_state, params
            )
        else:
            grads = sync_gradients(
                grads, data_axis, compression, axis_size=axis_size, key=rng
            )
            params, opt_state = _fenced_update(tx, grads, opt_state, params)
        return params, opt_state

    def stepper(params: PyTree, opt_state: PyTree, grads: PyTree):
        if level == "off":
            opt_specs: PyTree = P()
            param_specs: PyTree = P()
        else:
            # Name-matched rules place the chunked-params-derived opt
            # template identically (same treedef, same moment names), so
            # zero3 needs no canonical param shapes here.
            opt_specs = zero.opt_partition_specs(tx, params, level, data_axis)
            param_specs = P(data_axis) if level == "zero3" else P()
        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, opt_specs, P()),
            out_specs=(param_specs, opt_specs),
            check=False,
        )
        return sharded(params, opt_state, grads)

    return jax.jit(stepper, donate_argnums=(0, 1))


def make_eval_step_gspmd(
    model: nn.Module,
    mesh: Mesh,
    num_classes: int,
    data_axis: str = "data",
    space_axis: Optional[str] = "space",
) -> Callable[[TrainState, jax.Array, jax.Array], dict]:
    """GSPMD eval: batch [B,H,W,C] sharded over (data, space)."""

    def eval_fn(state: TrainState, images: jax.Array, labels: jax.Array):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )
        cm = confusion_from_logits(logits, labels, num_classes)
        nll_sum, count = softmax_cross_entropy_sum(logits, labels, ignore_index=-1)
        return {"confusion": cm, "loss_sum": nll_sum, "pixel_count": count}

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(data_axis, space_axis))
    return jax.jit(
        eval_fn,
        in_shardings=(repl, batch_sh, batch_sh),
        out_shardings=repl,
    )


def make_eval_step(
    model: nn.Module,
    mesh: Mesh,
    num_classes: int,
    data_axis: str = "data",
) -> Callable[[TrainState, jax.Array, jax.Array], dict]:
    """Jitted eval step: batch [B, H, W, C] sharded over data; returns summed
    confusion matrix [C, C] + mean loss (reference never evaluates held-out
    data, SURVEY §3.3 — this is the north-star mIoU path)."""

    def shard_body(state: TrainState, images: jax.Array, labels: jax.Array):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )
        cm = confusion_from_logits(logits, labels, num_classes)
        # -1 marks batch-padding pixels from the eval loader (data/loader.py).
        # Return summed NLL and valid-pixel count, not a mean: the caller
        # accumulates both across shards AND batches and divides once, so
        # padded shards/tail batches get exactly their valid-pixel weight.
        nll_sum, count = softmax_cross_entropy_sum(logits, labels, ignore_index=-1)
        return {
            "confusion": lax.psum(cm, data_axis),
            "loss_sum": lax.psum(nll_sum, data_axis),
            "pixel_count": lax.psum(count, data_axis),
        }

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P(data_axis)),
        out_specs=P(),
        check=False,
    )
    return jax.jit(sharded)


def make_predict_fn(
    model: nn.Module,
) -> Callable[[TrainState, jax.Array], jax.Array]:
    """Single-device jitted inference: images [N,H,W,C] → class map [N,H,W]."""

    @jax.jit
    def predict(state: TrainState, images: jax.Array) -> jax.Array:
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )
        return jnp.argmax(logits, axis=-1)

    return predict


def make_logits_fn(
    model: nn.Module,
) -> Callable[[TrainState, jax.Array], jax.Array]:
    """Single-device jitted inference returning raw logits [N,H,W,C] —
    the building block for sliding-window full-scene prediction, where
    overlapping windows blend *logits* (argmaxing per window first would
    make the overlap vote instead of average)."""

    @jax.jit
    def logits_fn(state: TrainState, images: jax.Array) -> jax.Array:
        return model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )

    return logits_fn
