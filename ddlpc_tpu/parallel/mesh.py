"""Device-mesh construction and multi-host bootstrap.

Replaces the reference's entire cluster layer — hand-rolled TCP star with
hostname→ID→IP tables, sequential accept loop and blocking point-to-point
broadcast/gather (кластер.py:172-252, 209-220) — with a
``jax.sharding.Mesh`` over which XLA emits collectives on ICI (intra-slice)
and DCN (inter-host).  Roles disappear: every process runs the same SPMD
program; there is no server.

Axes:
- ``data``  — data parallelism: batch sharded, params replicated, gradients
  all-reduced (the reference's only strategy, SURVEY §2 parallelism table).
- ``space`` — spatial sharding of the image H dimension with halo exchange,
  the conv-segmentation analog of sequence/context parallelism (for tiles too
  large for one chip's HBM).
- ``pipe``  — MPMD pipeline stages (arxiv 2412.14374): each index along the
  axis owns one contiguous group of model blocks; stages run as separate
  per-stage programs on disjoint (data, space) sub-meshes
  (:func:`stage_meshes`) driven by the host round-robin schedule in
  ``parallel/pipeline.py``.  Absent (the mesh stays 2-axis, bit-identical to
  pre-pipeline revisions) unless ``pipeline_stages > 1``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import ParallelConfig


def _distributed_client_active() -> bool:
    """True if jax.distributed.initialize() already ran in this process.

    Deliberately does NOT call jax.process_count() — that initializes the XLA
    backend, after which jax.distributed.initialize() refuses to run.
    """
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap.  MUST run before any other JAX call.

    The reference bootstraps by hostname lookup into a hard-coded IP table and
    a TCP accept loop (кластер.py:176-206,226-252).  Here a single call wires
    every host into one JAX runtime.  Arguments fall back to the
    ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID`` environment
    variables; on TPU pods / Slurm / OMPI, JAX auto-detects everything and a
    bare call suffices.  No-op when neither arguments nor environment request
    a multi-process run, so single-process users may call it unconditionally.
    """
    if _distributed_client_active():
        return
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS"
    ) or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.environ.get("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh(
    cfg: ParallelConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, space) mesh from available devices.

    ``data_axis_size=-1`` absorbs all devices not claimed by the space axis.
    Device order follows ``jax.devices()`` so the data axis maps to the
    outermost (DCN, then ICI) links and space stays within a host — the
    layout that keeps halo exchange on fast links.
    """
    devices = list(devices if devices is not None else jax.devices())
    space = max(1, cfg.space_axis_size)
    pipe = max(1, getattr(cfg, "pipeline_stages", 1))
    if len(devices) % (space * pipe):
        raise ValueError(
            f"space_axis_size={space} × pipeline_stages={pipe} does not "
            f"divide device count {len(devices)}"
        )
    data = cfg.data_axis_size
    if data == -1:
        data = len(devices) // (space * pipe)
    if pipe * data * space > len(devices):
        raise ValueError(
            f"mesh {pipe}×{data}×{space} (pipe×data×space) needs "
            f"{pipe * data * space} devices, only {len(devices)} available"
        )
    if pipe * data * space < len(devices):
        import warnings

        warnings.warn(
            f"mesh {pipe}×{data}×{space} uses {pipe * data * space} of "
            f"{len(devices)} devices; the rest stay idle",
            stacklevel=2,
        )
        devices = devices[: pipe * data * space]
    if pipe > 1:
        # pipe is OUTERMOST: a stage is a contiguous run of jax.devices(),
        # so the data/space collectives inside a stage stay on the fast
        # links and only the thin activation carry crosses stages — the
        # MPMD layout of arxiv 2412.14374.
        grid = np.array(devices).reshape(pipe, data, space)
        return Mesh(
            grid,
            (cfg.pipe_axis_name, cfg.data_axis_name, cfg.space_axis_name),
        )
    grid = np.array(devices).reshape(data, space)
    return Mesh(grid, (cfg.data_axis_name, cfg.space_axis_name))


def stage_meshes(mesh: Mesh, pipe_axis: str = "pipe") -> list:
    """Slice a (pipe, data, space) mesh into its per-stage (data, space)
    sub-meshes — one ``Mesh`` per index along the pipe axis, over disjoint
    device groups, axis names preserved.  The per-stage programs
    (``parallel/pipeline.py``) compile against these, so every in-stage
    collective (gradient wire, ZeRO chunk traffic, halo exchange) is scoped
    to the stage group.  A mesh without a pipe axis is its own single
    stage."""
    if pipe_axis not in mesh.axis_names:
        return [mesh]
    idx = mesh.axis_names.index(pipe_axis)
    if idx != 0:
        raise ValueError(
            f"pipe axis {pipe_axis!r} must be outermost, got mesh axes "
            f"{mesh.axis_names}"
        )
    rest = tuple(n for n in mesh.axis_names if n != pipe_axis)
    return [
        Mesh(mesh.devices[s], rest) for s in range(mesh.shape[pipe_axis])
    ]


def batch_sharding(mesh: Mesh, cfg: ParallelConfig) -> NamedSharding:
    """Sharding for a [B, H, W, C] batch: B over data, H over space."""
    return NamedSharding(mesh, P(cfg.data_axis_name, cfg.space_axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
