"""Device-mesh construction and multi-host bootstrap.

Replaces the reference's entire cluster layer — hand-rolled TCP star with
hostname→ID→IP tables, sequential accept loop and blocking point-to-point
broadcast/gather (кластер.py:172-252, 209-220) — with a
``jax.sharding.Mesh`` over which XLA emits collectives on ICI (intra-slice)
and DCN (inter-host).  Roles disappear: every process runs the same SPMD
program; there is no server.

Axes:
- ``data``  — data parallelism: batch sharded, params replicated, gradients
  all-reduced (the reference's only strategy, SURVEY §2 parallelism table).
- ``space`` — spatial sharding of the image H dimension with halo exchange,
  the conv-segmentation analog of sequence/context parallelism (for tiles too
  large for one chip's HBM).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import ParallelConfig


def _distributed_client_active() -> bool:
    """True if jax.distributed.initialize() already ran in this process.

    Deliberately does NOT call jax.process_count() — that initializes the XLA
    backend, after which jax.distributed.initialize() refuses to run.
    """
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap.  MUST run before any other JAX call.

    The reference bootstraps by hostname lookup into a hard-coded IP table and
    a TCP accept loop (кластер.py:176-206,226-252).  Here a single call wires
    every host into one JAX runtime.  Arguments fall back to the
    ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID`` environment
    variables; on TPU pods / Slurm / OMPI, JAX auto-detects everything and a
    bare call suffices.  No-op when neither arguments nor environment request
    a multi-process run, so single-process users may call it unconditionally.
    """
    if _distributed_client_active():
        return
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS"
    ) or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.environ.get("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh(
    cfg: ParallelConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, space) mesh from available devices.

    ``data_axis_size=-1`` absorbs all devices not claimed by the space axis.
    Device order follows ``jax.devices()`` so the data axis maps to the
    outermost (DCN, then ICI) links and space stays within a host — the
    layout that keeps halo exchange on fast links.
    """
    devices = list(devices if devices is not None else jax.devices())
    space = max(1, cfg.space_axis_size)
    if len(devices) % space:
        raise ValueError(
            f"space_axis_size={space} does not divide device count {len(devices)}"
        )
    data = cfg.data_axis_size
    if data == -1:
        data = len(devices) // space
    if data * space > len(devices):
        raise ValueError(
            f"mesh {data}×{space} (data×space) needs {data * space} devices, "
            f"only {len(devices)} available"
        )
    if data * space < len(devices):
        import warnings

        warnings.warn(
            f"mesh {data}×{space} uses {data * space} of {len(devices)} devices; "
            f"the rest stay idle",
            stacklevel=2,
        )
        devices = devices[: data * space]
    grid = np.array(devices).reshape(data, space)
    return Mesh(grid, (cfg.data_axis_name, cfg.space_axis_name))


def batch_sharding(mesh: Mesh, cfg: ParallelConfig) -> NamedSharding:
    """Sharding for a [B, H, W, C] batch: B over data, H over space."""
    return NamedSharding(mesh, P(cfg.data_axis_name, cfg.space_axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
