"""ZeRO-1/2/3 cross-replica sharding of gradients, params and optimizer state.

Data-parallel training replicates the optimizer state and redundantly runs
the identical weight update on every replica — for Adam that is 2× the
model in fp32 moments per device plus N copies of the same update FLOPs.
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336, PAPERS.md) observes the update is elementwise,
so it can be *sharded*; DeepSpeed named the resulting ladder ZeRO:

- **zero1** — the moments persist sharded.  The gradient sync stays a
  full all-reduce (any codec/transport composes, including the ring);
  each replica then updates only its 1/N chunk and all-gathers the fresh
  params.  Wire: all-reduce (2·P·w) + params all-gather (P·4).
- **zero2** — the moments AND the optimizer-boundary gradients persist
  sharded: the sync is a reduce-scatter (the fused int8/fp16
  ``psum_scatter`` wire already produces exactly these shards — zero2 is
  "stop all-gathering what we just scattered"), the update runs on the
  shards, one all-gather publishes the params.  Wire: reduce-scatter
  (P·w) + params all-gather (P·4) — strictly LESS than zero1, which is
  why the update A/B pins zero2 ≤ zero1.  This is the program PR 5
  introduced (then called "zero1" after the paper's stage-1 HBM effect
  on the moments; renamed now that the true stage-1 program exists —
  config values ``auto``/``on`` still resolve here, nothing breaks).
- **zero3** — params persist sharded too, as the same ``[N, K]`` chunks;
  each step starts by all-gathering them per leaf on demand for the
  forward/backward (freed after use — they are temporaries of the step),
  and the update's fresh chunks are NOT gathered at step end.  Same wire
  volume as zero2 with the all-gather moved from the tail of step *t* to
  the head of step *t+1*; per-device persistent HBM for params, grads
  and moments all scale 1/N.

The *which-leaf-shards* decision is no longer leaf-by-leaf code: the
declarative rule engine (``parallel/partition.py``,
``state_partition_rules``) matches ordered regexes against "/"-joined
leaf names and this module maps the resulting :class:`~partition.Decision`
trees onto chunk layouts (shard_map path) or GSPMD shardings — the same
table drives ``StateLayout``, the step builders' specs, the HBM gauges
and the checkpoint shard/gather fns.

Chunk layout: every sharded leaf is flattened row-major, zero-padded to
a multiple of the data-axis size N, and viewed as ``[N, K]`` chunks —
row ``r`` is replica ``r``'s shard.  The arithmetic lives in
``grad_sync.sync_gradients_scatter`` and the step builders
(``train_step.py``); checkpoints always store the canonical *gathered*
layout, so on-disk blobs are layout-independent (docs/SHARDING.md).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.parallel import partition

PyTree = Any

# The shard_map chunk layouts, the GSPMD leaf layouts, and both
# families' logical ZeRO level (which state_partition_rules table they
# read).
CHUNK_LAYOUTS = ("zero1", "zero2", "zero3")
GSPMD_LAYOUTS = ("gspmd", "gspmd_zero2", "gspmd_zero3")
LAYOUT_LEVEL = {
    "replicated": "replicated",
    "zero1": "zero1",
    "zero2": "zero2",
    "zero3": "zero3",
    "gspmd": "zero1",
    "gspmd_zero2": "zero2",
    "gspmd_zero3": "zero3",
}
# Level → the GSPMD-family layout with the same persisted-state sharding
# (the Trainer's mode pick on data×space meshes; inverse of LAYOUT_LEVEL
# restricted to the GSPMD family).
GSPMD_LAYOUT_FOR_LEVEL = {
    "zero1": "gspmd",
    "zero2": "gspmd_zero2",
    "zero3": "gspmd_zero3",
}


def normalize_shard_update(value) -> str:
    """Step builders accept the historical bool (``True`` = the sharded
    program, which is zero2) or a level string — one knob, one meaning."""
    if value is True:
        return "zero2"
    if value is False or value is None or value == "off":
        return "off"
    if value in CHUNK_LAYOUTS:
        return value
    raise ValueError(
        f"unknown shard_update level {value!r} "
        f"(expected off|zero1|zero2|zero3 or a bool)"
    )


# ---------------------------------------------------------------------------
# chunk layout primitives


def chunk_rows(n_elements: int, n_shards: int) -> int:
    """K: elements per shard for an ``n_elements`` leaf over ``n_shards``."""
    return -(-n_elements // n_shards)


def chunk_leaf(x: jax.Array, n_shards: int) -> jax.Array:
    """Flatten ``x`` row-major, zero-pad to a multiple of ``n_shards``, and
    view as ``[n_shards, K]`` — row ``r`` is replica ``r``'s shard."""
    x = jnp.asarray(x)
    k = chunk_rows(x.size, n_shards)
    flat = x.reshape(-1)
    pad = n_shards * k - x.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n_shards, k)


def unchunk_leaf(chunked: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    """Inverse of :func:`chunk_leaf`: drop the padding, restore ``shape``."""
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return chunked.reshape(-1)[:size].reshape(shape)


def local_chunk(x: jax.Array, n_shards: int, axis_name: str) -> jax.Array:
    """This replica's ``[1, K]`` row of ``x``'s chunk view — call inside
    shard_map (uses ``lax.axis_index``)."""
    from jax import lax

    return lax.dynamic_slice_in_dim(
        chunk_leaf(x, n_shards), lax.axis_index(axis_name), 1, axis=0
    )


# ---------------------------------------------------------------------------
# which state leaves are sharded


def param_shapes(params: PyTree) -> frozenset:
    return frozenset(tuple(l.shape) for l in jax.tree.leaves(params))


def validate_zero1_params(params: PyTree) -> None:
    """Refuse 0-d parameters in the chunk layouts, loudly: the chunk rule
    identifies an optimizer leaf as a moment by its parameter shape, and
    ``chunkable`` excludes ``()`` precisely because Adam's ``count`` and
    schedule scalars are also ``()`` — a 0-d *parameter* would make its
    moments ambiguous with those (and the chunked grads/params would then
    diverge in shape from the unchunked moments inside ``tx.update``).  No
    model in this repo has scalar learnables; if one appears, reshape it to
    ``(1,)`` or run with ``shard_update='off'``."""
    bad = [
        jax.tree_util.keystr(path)
        for path, l in jax.tree_util.tree_leaves_with_path(params)
        if len(l.shape) == 0
    ]
    if bad:
        raise ValueError(
            f"shard_update (chunk layouts) cannot represent 0-d parameters "
            f"{bad} — reshape them to (1,) or set shard_update='off' "
            f"(parallel/shard_update.py:validate_zero1_params)"
        )


def chunkable(shape: Tuple[int, ...], pshapes: frozenset) -> bool:
    """A (full-layout) optimizer leaf is sharded iff it is parameter-shaped:
    Adam/SGD moments mirror the parameter tree leaf-for-leaf; step counters
    and schedule scalars are not parameter-shaped and stay replicated.
    (The rule engine's name match is the intent; this shape check remains
    the safety gate — ``partition.decide``'s ``param_shaped``.)"""
    return len(shape) > 0 and tuple(shape) in pshapes


def opt_state_template(tx, params: PyTree) -> PyTree:
    """Abstract full-layout opt_state (shapes/dtypes only, no allocation) —
    the reference against which chunked leaves are recognized and
    un-chunked (it carries their original shapes)."""
    shapes = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
    )
    return jax.eval_shape(tx.init, shapes)


# ---------------------------------------------------------------------------
# config resolution


def resolve_shard_update(
    mode: str,
    compression: CompressionConfig,
    data_size: int,
    spatial: bool,
    grad_clip_norm: float = 0.0,
) -> str:
    """Resolve ``ParallelConfig.shard_update`` to a ZeRO level string
    (``'off' | 'zero1' | 'zero2' | 'zero3'``).

    ``auto`` (the default) and ``on`` resolve to **zero2** — the program
    this repo has shipped since PR 5 (then labelled zero1; see the module
    docstring), so existing configs keep their exact step program.
    ``auto`` falls back to ``off`` on singleton meshes and on the
    combinations the scatter path cannot reproduce bit-identically;
    explicit levels (and ``on``) refuse those loudly instead of silently
    changing semantics:

    - ``transport='ring'`` (zero2/zero3 only): the ring owns its own
      quantized reduce-scatter/all-gather over whole leaves
      (compressed_allreduce.py) — splitting the mean stage across
      replicas would change which elements share a wire word.  **zero1
      composes**: its sync is the unmodified full all-reduce, the ring
      included; the chunking happens after the mean exists everywhere.
    - ``codec_backend='pallas'`` with ``quantize_mean`` (zero2/zero3
      only): the kernel draws its rounding noise from the TPU hardware
      PRNG per block, which cannot be sliced to a replica's shard of the
      mean.  **zero1 composes** for the same reason as the ring: the
      codec sees the full mean.
    - ``grad_clip_norm > 0`` (every chunked level): ``optax.
      clip_by_global_norm`` runs *inside* ``tx.update``, which all three
      chunk layouts call on each replica's 1/N shard — every replica
      would clip by its own shard's norm instead of the global norm
      (wrong threshold, replica-divergent updates).

    The GSPMD (spatial) path has none of these constraints: its codec and
    optimizer run on the full logical arrays inside the partitioned
    program (``optax.global_norm`` there is a partitioner-inserted psum),
    so only the mesh size gates.  The Trainer maps the returned level to
    the GSPMD layout family (``StateLayout``) on data×space meshes.
    """
    if mode not in ("auto", "on", "off", "zero1", "zero2", "zero3"):
        raise ValueError(
            f"unknown shard_update {mode!r} (expected 'auto', 'on', 'off', "
            f"'zero1', 'zero2' or 'zero3')"
        )
    if mode == "off":
        return "off"
    level = "zero2" if mode in ("auto", "on") else mode
    incompatible = None
    if not spatial:
        scatter_based = level in ("zero2", "zero3")
        if scatter_based and compression.mode != "none":
            if compression.transport == "ring":
                incompatible = (
                    "transport='ring' — the ring all-reduce owns its own "
                    "quantized reduce-scatter/all-gather over whole leaves "
                    "(shard_update='zero1' composes with the ring)"
                )
            elif (
                compression.quantize_mean
                and compression.codec_backend == "pallas"
            ):
                incompatible = (
                    "codec_backend='pallas' with quantize_mean — the "
                    "kernel's hardware-PRNG noise field cannot be sliced to "
                    "a shard of the mean; use codec_backend='xla' or "
                    "shard_update='zero1'"
                )
        if incompatible is None and grad_clip_norm:
            incompatible = (
                "grad_clip_norm > 0 — optax.clip_by_global_norm inside "
                "tx.update would clip each replica's 1/N shard by its own "
                "partial norm, not the global norm; use a data×space mesh "
                "(GSPMD path) or disable clipping"
            )
    if mode != "auto":
        if incompatible:
            raise ValueError(
                f"shard_update={mode!r} cannot compose with {incompatible}; "
                f"set shard_update='off' (or 'auto', which resolves it)"
            )
        # Singleton mesh: sharding into 1 shard is the replicated program —
        # fall back to it rather than carry a degenerate chunk layout.
        return level if data_size > 1 else "off"
    return level if data_size > 1 and incompatible is None else "off"


# ---------------------------------------------------------------------------
# rule-engine decision trees over the state


def opt_decisions(
    tx, params: PyTree, layout: str, n_shards: int, data_axis: str = "data"
) -> PyTree:
    """Partition decisions for the full-layout opt_state template under
    ``layout`` — the rule table (``partition.state_partition_rules``) is
    the intent, the parameter-shape set the safety gate."""
    template = opt_state_template(tx, params)
    mode = "chunk" if layout in CHUNK_LAYOUTS else "leaf"
    return partition.decide_tree(
        partition.state_partition_rules(LAYOUT_LEVEL[layout]),
        template, "opt_state",
        mode=mode, n_shards=n_shards, data_axis=data_axis,
        pshapes=param_shapes(params),
    )


def param_decisions(
    params: PyTree, layout: str, n_shards: int, data_axis: str = "data",
    prefix: str = "params",
) -> PyTree:
    """Partition decisions for the params (or, with ``prefix='grads'``,
    the optimizer-boundary gradient) tree under ``layout``."""
    mode = "chunk" if layout in CHUNK_LAYOUTS else "leaf"
    return partition.decide_tree(
        partition.state_partition_rules(LAYOUT_LEVEL[layout]),
        params, prefix,
        mode=mode, n_shards=n_shards, data_axis=data_axis,
    )


def opt_leaf_spec(
    shape: Tuple[int, ...],
    pshapes: frozenset,
    layout: str,
    n_shards: int,
    data_axis: str,
) -> Optional[P]:
    """Run-layout partition spec for ONE full-layout optimizer leaf — the
    per-leaf form of the rule engine's decision, kept for callers that
    iterate leaves themselves (the GSPMD builder's constraint loop).
    Returns ``None`` for leaves that are not parameter-shaped (step
    counters, schedule scalars): they stay replicated and get no
    sharding constraint."""
    if not chunkable(shape, pshapes):
        return None
    if layout in CHUNK_LAYOUTS:
        return P(data_axis)
    return zero_leaf_spec(shape, n_shards, data_axis)


def opt_partition_specs(
    tx, params: PyTree, layout: str, data_axis: str, n_shards: int = 1
) -> PyTree:
    """PartitionSpec tree over the full-layout opt_state template for the
    run ``layout`` (shard_map in_specs/out_specs form; non-sharded leaves
    → ``P()``).  ``n_shards`` only matters for the GSPMD layouts."""
    if layout in CHUNK_LAYOUTS:
        validate_zero1_params(params)
    decisions = opt_decisions(tx, params, layout, n_shards, data_axis)
    return jax.tree.map(lambda d: d.spec, decisions)


def _decision_shardings(decisions: PyTree, mesh: Mesh) -> PyTree:
    repl = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda d: repl if not d.sharded else NamedSharding(mesh, d.spec),
        decisions,
    )


def opt_shardings(
    tx, params: PyTree, layout: str, mesh: Mesh, data_axis: str
) -> PyTree:
    """NamedSharding tree (jit in_shardings / device_put form) for the run
    ``layout`` of the optimizer state — same decisions as
    :func:`opt_partition_specs`, mesh-attached."""
    if layout == "replicated":
        template = opt_state_template(tx, params)
        repl = NamedSharding(mesh, P())
        return jax.tree.map(lambda t: repl, template)
    return _decision_shardings(
        opt_decisions(tx, params, layout, mesh.shape[data_axis], data_axis),
        mesh,
    )


def zero_leaf_spec(
    shape: Tuple[int, ...], n_shards: int, data_axis: str
) -> P:
    """GSPMD ZeRO spec for a param-shaped leaf: partition the largest
    evenly-divisible dimension; no such dimension → replicated.  The
    pick itself lives in the rule engine (:func:`partition.even_shard_spec`
    — the same resolver every SHARD rule uses), and leaves it replicates
    carry the explicit ``replicated-by-rule`` decision the sharding
    contract and HBM gauges budget."""
    return partition.even_shard_spec(shape, n_shards, data_axis)


class StateLayout:
    """Converts a ``TrainState`` between the canonical replicated layout
    (what checkpoints store, what ``create_train_state`` builds) and the
    run layout the train step consumes.

    shard_map (chunk) family — sharded leaves live as ``[N, K]`` chunk
    views sharded ``P(data)``, each device holding one ``[1, K]`` row:

    - ``mode='zero1'`` / ``'zero2'``: the optimizer moments chunk; params
      stay replicated (the forward needs them whole).  The two levels
      place identically — they differ only in the step program's wire
      (zero1 all-reduces the mean then slices; zero2 keeps the
      reduce-scattered shards).
    - ``mode='zero3'``: params chunk too; the step all-gathers them on
      demand (train_step.py) and checkpoints/eval gather via
      :meth:`full_params`.

    GSPMD (leaf) family — sharded leaves keep their parameter shapes but
    carry ``P(..., data, ...)`` shardings (``partition.even_shard_spec``
    picks the dimension); the XLA partitioner inserts the collectives:

    - ``mode='gspmd'``: moments sharded (the PR 5 behavior).
    - ``mode='gspmd_zero2'``: same placement; the step additionally pins
      the mean gradient's shardings so the partitioner materializes a
      reduce-scatter instead of an all-reduce.
    - ``mode='gspmd_zero3'``: params sharded at the state boundary too.

    ``place``/``canonical`` are jitted once and cached — at checkpoint
    cadence a retrace per save would otherwise recompile the gather every
    epoch.  Both are collectives under multi-host meshes, so every process
    must call them (Trainer.save/restore do).  The per-leaf chunk/unchunk
    callables come from ``partition.make_shard_and_gather_fns`` over the
    same decision trees that build the sharding specs — one table, no
    drift.
    """

    MODES = ("replicated",) + CHUNK_LAYOUTS + GSPMD_LAYOUTS

    def __init__(
        self,
        mode: str,
        tx,
        state: PyTree,
        mesh: Mesh,
        data_axis: str = "data",
    ):
        if mode not in self.MODES:
            raise ValueError(f"unknown state layout {mode!r}")
        self.mesh = mesh
        self.data_axis = data_axis
        self.n = mesh.shape[data_axis]
        # Singleton data mesh: one shard IS the replicated layout — mirror
        # the step builders' fallback so layout and step cannot disagree.
        self.mode = mode if self.n > 1 else "replicated"
        self.level = LAYOUT_LEVEL[self.mode]
        self.chunk = self.mode in CHUNK_LAYOUTS
        self.chunk_params = self.mode == "zero3"
        self.sharded_params = self.mode in ("zero3", "gspmd_zero3")
        if self.chunk:
            validate_zero1_params(state.params)
        self._repl = NamedSharding(mesh, P())
        self._template = opt_state_template(tx, state.params)
        self._pshapes = param_shapes(state.params)
        self.param_avals = jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), state.params
        )
        pmode = "chunk" if self.chunk or self.mode == "replicated" else "leaf"
        rules = partition.state_partition_rules(self.level)
        self.opt_decisions = partition.decide_tree(
            rules, self._template, "opt_state",
            mode=pmode, n_shards=self.n, data_axis=data_axis,
            pshapes=self._pshapes,
        )
        self.param_decisions = partition.decide_tree(
            rules, state.params, "params",
            mode=pmode, n_shards=self.n, data_axis=data_axis,
        )
        self.grad_decisions = partition.decide_tree(
            rules, state.params, "grads",
            mode=pmode, n_shards=self.n, data_axis=data_axis,
        )
        self._opt_shard_fns, self._opt_gather_fns = (
            partition.make_shard_and_gather_fns(
                self.opt_decisions, self.n, pmode
            )
        )
        self._param_shard_fns, self._param_gather_fns = (
            partition.make_shard_and_gather_fns(
                self.param_decisions, self.n, pmode
            )
        )
        self._place_fn = None
        self._canonical_fn = None
        self._full_params_fn = None

    # -- sharding trees -----------------------------------------------------

    def _chunk_aware_sharding(self, decision) -> NamedSharding:
        """Chunk-mode sharded leaves change shape ([N, K]); their spec
        P(data) applies to the chunk view — NamedSharding is shape-blind,
        so the same object covers both families."""
        if not decision.sharded:
            return self._repl
        return NamedSharding(self.mesh, decision.spec)

    def _opt_shardings(self) -> PyTree:
        return jax.tree.map(self._chunk_aware_sharding, self.opt_decisions)

    def _param_shardings(self) -> PyTree:
        if not self.sharded_params:
            return jax.tree.map(lambda _: self._repl, self.param_decisions)
        return jax.tree.map(self._chunk_aware_sharding, self.param_decisions)

    def state_shardings(self, state: PyTree) -> PyTree:
        """Per-leaf NamedSharding tree for the RUN layout of ``state``."""
        return state.replace(
            step=self._repl,
            params=self._param_shardings(),
            batch_stats=jax.tree.map(lambda _: self._repl, state.batch_stats),
            opt_state=self._opt_shardings(),
        )

    def replicated_by_rule_bytes(self) -> int:
        """Per-device bytes of leaves the rule engine decided to keep
        replicated (uneven GSPMD dims) — the ``ddlpc_hbm`` budget line."""
        total = 0
        for dec, tree in (
            (self.opt_decisions, self._template),
            (self.param_decisions, self.param_avals),
        ):
            total += partition.replicated_by_rule_bytes(dec, tree)
        return total

    # -- layout conversion --------------------------------------------------

    def place(self, state: PyTree) -> PyTree:
        """Canonical (full, replicated-shape) state → run layout on mesh."""
        if self.mode == "replicated":
            return jax.device_put(state, self._repl)
        if self._place_fn is None:
            shardings = self.state_shardings(state)

            def to_run(s):
                opt = jax.tree.map(
                    lambda f, l: f(l), self._opt_shard_fns, s.opt_state
                )
                params = jax.tree.map(
                    lambda f, l: f(l), self._param_shard_fns, s.params
                )
                return s.replace(params=params, opt_state=opt)

            self._place_fn = jax.jit(to_run, out_shardings=shardings)
        return self._place_fn(state)

    def canonical(self, state: PyTree) -> PyTree:
        """Run layout → canonical full replicated layout (the checkpoint/
        broadcast layout).  For sharded modes this compiles to an
        all-gather of the sharded leaves — transiently materializing the
        full state once per checkpoint, never per step."""
        if self.mode == "replicated":
            return state
        if self._canonical_fn is None:

            def to_full(s):
                opt = jax.tree.map(
                    lambda f, l: f(l), self._opt_gather_fns, s.opt_state
                )
                params = jax.tree.map(
                    lambda f, l: f(l), self._param_gather_fns, s.params
                )
                return s.replace(params=params, opt_state=opt)

            self._canonical_fn = jax.jit(to_full, out_shardings=self._repl)
        return self._canonical_fn(state)

    def full_params(self, state: PyTree) -> PyTree:
        """Canonical-shape replicated params from the run layout — what
        eval/predict/serve consume.  Identity for layouts that keep
        params whole; a compiled all-gather (chunked or GSPMD-sharded →
        replicated) under zero3/gspmd_zero3.  Gathers ONLY the params,
        not the moments — eval must not pay the checkpoint gather."""
        if not self.sharded_params:
            return state.params
        if self._full_params_fn is None:
            repl = jax.tree.map(lambda _: self._repl, self.param_avals)

            def gather(params):
                return jax.tree.map(
                    lambda f, l: f(l), self._param_gather_fns, params
                )

            self._full_params_fn = jax.jit(gather, out_shardings=repl)
        return self._full_params_fn(state.params)
