"""ZeRO-1 cross-replica sharding of the optimizer update.

Data-parallel training replicates the optimizer state and redundantly runs
the identical weight update on every replica — for Adam that is 2× the
model in fp32 moments per device plus N copies of the same update FLOPs.
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336, PAPERS.md) observes the update is elementwise,
so it can be *sharded*: reduce-scatter the gradients (each replica receives
the mean of 1/N of the elements), update 1/N of the parameters and moments,
then all-gather the fresh parameters for the next forward.  Communication
volume is unchanged (all-reduce ≡ reduce-scatter + all-gather); optimizer
HBM and update FLOPs divide by N.

This module owns the *layout*: every parameter leaf is flattened, padded to
a multiple of the data-axis size N, and viewed as ``[N, K]`` chunks — row
``r`` is replica ``r``'s shard.  Row-major flattening makes the chunk view
of an already-``[N, K]``-shaped leaf the identity, so the rule "an optimizer
leaf is chunked iff its unsharded shape equals some parameter's shape"
(Adam's ``mu``/``nu`` and SGD's ``trace`` mirror the parameter tree;
``count`` and the schedule scalars do not) is unambiguous.  The arithmetic
lives in ``grad_sync.sync_gradients_scatter`` and the step builders
(``train_step.py``); checkpoints always store the canonical *gathered*
layout, so on-disk blobs are layout-independent (docs/SHARDING.md).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import CompressionConfig

PyTree = Any


# ---------------------------------------------------------------------------
# chunk layout primitives


def chunk_rows(n_elements: int, n_shards: int) -> int:
    """K: elements per shard for an ``n_elements`` leaf over ``n_shards``."""
    return -(-n_elements // n_shards)


def chunk_leaf(x: jax.Array, n_shards: int) -> jax.Array:
    """Flatten ``x`` row-major, zero-pad to a multiple of ``n_shards``, and
    view as ``[n_shards, K]`` — row ``r`` is replica ``r``'s shard."""
    x = jnp.asarray(x)
    k = chunk_rows(x.size, n_shards)
    flat = x.reshape(-1)
    pad = n_shards * k - x.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n_shards, k)


def unchunk_leaf(chunked: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    """Inverse of :func:`chunk_leaf`: drop the padding, restore ``shape``."""
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return chunked.reshape(-1)[:size].reshape(shape)


def local_chunk(x: jax.Array, n_shards: int, axis_name: str) -> jax.Array:
    """This replica's ``[1, K]`` row of ``x``'s chunk view — call inside
    shard_map (uses ``lax.axis_index``)."""
    from jax import lax

    return lax.dynamic_slice_in_dim(
        chunk_leaf(x, n_shards), lax.axis_index(axis_name), 1, axis=0
    )


# ---------------------------------------------------------------------------
# which optimizer-state leaves are sharded


def param_shapes(params: PyTree) -> frozenset:
    return frozenset(tuple(l.shape) for l in jax.tree.leaves(params))


def validate_zero1_params(params: PyTree) -> None:
    """Refuse 0-d parameters in the zero1 layout, loudly: the chunk rule
    identifies an optimizer leaf as a moment by its parameter shape, and
    ``chunkable`` excludes ``()`` precisely because Adam's ``count`` and
    schedule scalars are also ``()`` — a 0-d *parameter* would make its
    moments ambiguous with those (and the chunked grads/params would then
    diverge in shape from the unchunked moments inside ``tx.update``).  No
    model in this repo has scalar learnables; if one appears, reshape it to
    ``(1,)`` or run with ``shard_update='off'``."""
    bad = [
        jax.tree_util.keystr(path)
        for path, l in jax.tree_util.tree_leaves_with_path(params)
        if len(l.shape) == 0
    ]
    if bad:
        raise ValueError(
            f"shard_update (zero1 layout) cannot represent 0-d parameters "
            f"{bad} — reshape them to (1,) or set shard_update='off' "
            f"(parallel/shard_update.py:validate_zero1_params)"
        )


def chunkable(shape: Tuple[int, ...], pshapes: frozenset) -> bool:
    """A (full-layout) optimizer leaf is sharded iff it is parameter-shaped:
    Adam/SGD moments mirror the parameter tree leaf-for-leaf; step counters
    and schedule scalars are not parameter-shaped and stay replicated."""
    return len(shape) > 0 and tuple(shape) in pshapes


def opt_state_template(tx, params: PyTree) -> PyTree:
    """Abstract full-layout opt_state (shapes/dtypes only, no allocation) —
    the reference against which chunked leaves are recognized and
    un-chunked (it carries their original shapes)."""
    shapes = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
    )
    return jax.eval_shape(tx.init, shapes)


# ---------------------------------------------------------------------------
# config resolution


def resolve_shard_update(
    mode: str,
    compression: CompressionConfig,
    data_size: int,
    spatial: bool,
    grad_clip_norm: float = 0.0,
) -> bool:
    """Resolve ``ParallelConfig.shard_update`` ∈ {auto, on, off} to a bool.

    ``auto`` (the default) turns sharding on for data meshes > 1 and off
    for singleton meshes and for the three combinations the shard_map
    path cannot reproduce bit-identically (explicit ``on`` refuses those
    loudly instead of silently changing semantics):

    - ``transport='ring'``: the ring owns its own full-tree quantized
      reduce-scatter/all-gather (compressed_allreduce.py) whose integer
      wire sums are defined over whole leaves — splitting the mean stage
      across replicas would change which elements share a wire word.
    - ``codec_backend='pallas'`` with ``quantize_mean``: the kernel draws
      its rounding noise from the TPU hardware PRNG per block, which
      cannot be sliced to a replica's shard of the mean; the XLA backend's
      threefry field can (grad_sync.sync_gradients_scatter).
    - ``grad_clip_norm > 0``: ``optax.clip_by_global_norm`` runs *inside*
      ``tx.update``, which the chunked path calls on each replica's 1/N
      shard — every replica would clip by the norm of its own shard
      instead of the global norm (wrong threshold, replica-divergent
      updates).  The clip stage cannot see the cross-replica sum from
      inside an opaque optax chain.

    The GSPMD (spatial) path has none of these constraints: its codec and
    optimizer run on the full logical arrays inside the partitioned
    program (``optax.global_norm`` there is a partitioner-inserted psum),
    so only the mesh size gates.
    """
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"unknown shard_update {mode!r} (expected 'auto', 'on' or 'off')"
        )
    if mode == "off":
        return False
    incompatible = None
    if not spatial and compression.mode != "none":
        if compression.transport == "ring":
            incompatible = (
                "transport='ring' — the ring all-reduce owns its own "
                "quantized reduce-scatter/all-gather over whole leaves"
            )
        elif compression.quantize_mean and compression.codec_backend == "pallas":
            incompatible = (
                "codec_backend='pallas' with quantize_mean — the kernel's "
                "hardware-PRNG noise field cannot be sliced to a shard of "
                "the mean; use codec_backend='xla'"
            )
    if not spatial and incompatible is None and grad_clip_norm:
        incompatible = (
            "grad_clip_norm > 0 — optax.clip_by_global_norm inside "
            "tx.update would clip each replica's 1/N shard by its own "
            "partial norm, not the global norm; use a data×space mesh "
            "(GSPMD path) or disable clipping"
        )
    if mode == "on":
        if incompatible:
            raise ValueError(
                f"shard_update='on' cannot compose with {incompatible}; set "
                f"shard_update='off' (or 'auto', which resolves it)"
            )
        # Singleton mesh: sharding into 1 shard is the replicated program —
        # fall back to it rather than carry a degenerate chunk layout.
        return data_size > 1
    return data_size > 1 and incompatible is None


# ---------------------------------------------------------------------------
# state layout: replicated | zero1 (chunked, shard_map) | gspmd (leaf-sharded)


def opt_leaf_spec(
    shape: Tuple[int, ...],
    pshapes: frozenset,
    layout: str,
    n_shards: int,
    data_axis: str,
) -> Optional[P]:
    """Run-layout partition spec for ONE full-layout optimizer leaf — the
    single owner of the which-leaves-shard-and-how decision, shared by
    every site that builds opt-state specs (``StateLayout``, both step
    builders, ``make_update_step``) so the trainer's placement and the
    steps' in/out specs cannot drift apart.  Returns ``None`` for leaves
    that are not parameter-shaped (step counters, schedule scalars): they
    stay replicated and get no sharding constraint."""
    if not chunkable(shape, pshapes):
        return None
    if layout == "zero1":
        return P(data_axis)
    return zero_leaf_spec(shape, n_shards, data_axis)


def opt_partition_specs(
    tx, params: PyTree, layout: str, data_axis: str, n_shards: int = 1
) -> PyTree:
    """PartitionSpec tree over the full-layout opt_state template for the
    run ``layout`` (shard_map in_specs/out_specs form; non-param-shaped
    leaves → ``P()``).  ``n_shards`` only matters for ``layout='gspmd'``."""
    if layout == "zero1":
        validate_zero1_params(params)
    template = opt_state_template(tx, params)
    pshapes = param_shapes(params)

    def leaf(t):
        sp = opt_leaf_spec(t.shape, pshapes, layout, n_shards, data_axis)
        return P() if sp is None else sp

    return jax.tree.map(leaf, template)


def _map_opt_shardings(
    template: PyTree, pshapes: frozenset, layout: str, mesh: Mesh,
    data_axis: str,
) -> PyTree:
    """Map :func:`opt_leaf_spec` over a full-layout opt_state template as a
    NamedSharding tree — the one implementation behind both the function
    and :class:`StateLayout` forms, so they cannot drift."""
    repl = NamedSharding(mesh, P())
    if layout == "replicated":
        return jax.tree.map(lambda t: repl, template)
    n = mesh.shape[data_axis]

    def leaf(t):
        sp = opt_leaf_spec(t.shape, pshapes, layout, n, data_axis)
        return repl if sp is None else NamedSharding(mesh, sp)

    return jax.tree.map(leaf, template)


def opt_shardings(
    tx, params: PyTree, layout: str, mesh: Mesh, data_axis: str
) -> PyTree:
    """NamedSharding tree (jit in_shardings / device_put form) for the run
    ``layout`` of the optimizer state — same decisions as
    :func:`opt_partition_specs`, mesh-attached."""
    return _map_opt_shardings(
        opt_state_template(tx, params), param_shapes(params), layout, mesh,
        data_axis,
    )


def zero_leaf_spec(
    shape: Tuple[int, ...], n_shards: int, data_axis: str
) -> P:
    """GSPMD ZeRO spec for a param-shaped optimizer leaf: partition the
    largest dimension that divides EVENLY by the data axis; leaves with
    no such dimension stay replicated.  (An uneven pick used to fall
    back to the largest dimension ≥ N on the theory that GSPMD pads —
    but an uneven NamedSharding is rejected by ``jit in_shardings`` at
    the state boundary, so any model with e.g. a 6-class bias on a 4-way
    mesh crashed at placement.  Surfaced by the compiled-program auditor,
    docs/ANALYSIS.md "Program-level contracts"; such leaves are a
    rounding error of the moment bytes, so replicating them costs ~0.)"""
    if not shape:
        return P()
    pick = None
    for d in sorted(range(len(shape)), key=lambda d: shape[d], reverse=True):
        if shape[d] >= n_shards and shape[d] % n_shards == 0:
            pick = d
            break
    if pick is None:
        return P()
    spec = [None] * len(shape)
    spec[pick] = data_axis
    return P(*spec)


class StateLayout:
    """Converts a ``TrainState`` between the canonical replicated layout
    (what checkpoints store, what ``create_train_state`` builds) and the
    run layout the train step consumes.

    - ``mode='replicated'``: run layout == canonical layout.
    - ``mode='zero1'`` (shard_map step): opt-state moments live as
      ``[N, K]`` chunk leaves sharded ``P(data)`` over the mesh — each
      device holds one ``[1, K]`` row; params stay replicated (the forward
      needs them whole).
    - ``mode='gspmd'``: opt-state moments keep their parameter shapes but
      are partitioned ``P(..., data, ...)`` per :func:`zero_leaf_spec`; the
      XLA partitioner inserts the reduce-scatter/all-gather around the
      update on its own.

    ``place``/``canonical`` are jitted once and cached — at checkpoint
    cadence a retrace per save would otherwise recompile the gather every
    epoch.  Both are collectives under multi-host meshes, so every process
    must call them (Trainer.save/restore do).
    """

    def __init__(
        self,
        mode: str,
        tx,
        state: PyTree,
        mesh: Mesh,
        data_axis: str = "data",
    ):
        if mode not in ("replicated", "zero1", "gspmd"):
            raise ValueError(f"unknown state layout {mode!r}")
        self.mesh = mesh
        self.data_axis = data_axis
        self.n = mesh.shape[data_axis]
        # Singleton data mesh: one shard IS the replicated layout — mirror
        # the step builders' fallback so layout and step cannot disagree.
        self.mode = mode if self.n > 1 else "replicated"
        if self.mode == "zero1":
            validate_zero1_params(state.params)
        self._repl = NamedSharding(mesh, P())
        self._template = opt_state_template(tx, state.params)
        self._pshapes = param_shapes(state.params)
        self._place_fn = None
        self._canonical_fn = None

    # -- sharding trees -----------------------------------------------------

    def _opt_shardings(self) -> PyTree:
        return _map_opt_shardings(
            self._template, self._pshapes, self.mode, self.mesh,
            self.data_axis,
        )

    def state_shardings(self, state: PyTree) -> PyTree:
        """Per-leaf NamedSharding tree for the RUN layout of ``state``."""
        return state.replace(
            step=self._repl,
            params=jax.tree.map(lambda _: self._repl, state.params),
            batch_stats=jax.tree.map(lambda _: self._repl, state.batch_stats),
            opt_state=self._opt_shardings(),
        )

    # -- layout conversion --------------------------------------------------

    def place(self, state: PyTree) -> PyTree:
        """Canonical (full, replicated-shape) state → run layout on mesh."""
        if self.mode == "replicated":
            return jax.device_put(state, self._repl)
        if self._place_fn is None:
            shardings = self.state_shardings(state)
            if self.mode == "zero1":
                n = self.n

                def to_run(s):
                    opt = jax.tree.map(
                        lambda t, l: chunk_leaf(l, n)
                        if chunkable(t.shape, self._pshapes)
                        else l,
                        self._template,
                        s.opt_state,
                    )
                    return s.replace(opt_state=opt)

            else:  # gspmd: same shapes, different placement

                def to_run(s):
                    return s

            self._place_fn = jax.jit(to_run, out_shardings=shardings)
        return self._place_fn(state)

    def canonical(self, state: PyTree) -> PyTree:
        """Run layout → canonical full replicated layout (the checkpoint/
        broadcast layout).  For sharded modes this compiles to an
        all-gather of the moments — transiently materializing the full
        optimizer state once per checkpoint, never per step."""
        if self.mode == "replicated":
            return state
        if self._canonical_fn is None:
            if self.mode == "zero1":
                def to_full(s):
                    opt = jax.tree.map(
                        lambda t, l: unchunk_leaf(l, t.shape)
                        if chunkable(t.shape, self._pshapes)
                        else l,
                        self._template,
                        s.opt_state,
                    )
                    return s.replace(opt_state=opt)

            else:

                def to_full(s):
                    return s

            self._canonical_fn = jax.jit(to_full, out_shardings=self._repl)
        return self._canonical_fn(state)
