"""MPMD pipeline parallelism: staged encoder–decoder execution along a
third ``pipe`` mesh axis (docs/SHARDING.md "Pipeline stages").

The last parallelism family in PAPERS.md with no answer here (arxiv
2412.14374 "Scaling Deep Learning Training with MPMD Pipeline
Parallelism"; arxiv 2204.06514 for pjit-era pod meshes): cut the model
into ``S`` contiguous stages, give each stage its own (data, space)
sub-mesh (``parallel/mesh.py:stage_meshes``), and drive them with a
GPipe-style microbatch round-robin — the reference's 50-microbatch
gradient-accumulation loop (кластер.py:750-759) is exactly the microbatch
stream a pipeline schedule feeds on.

Decomposition of ``train_step.py``'s monolithic builders, piece by piece:

- **stage assignment** is a declarative regex rule table
  (``parallel/partition.py:StageRule``, the ZeRO-table pattern one level
  up): one anchored rule per model block, generated from a balanced
  contiguous partition of per-block parameter bytes
  (``balanced_stage_assignment``), first match wins, an uncovered leaf
  raises.
- **forward/backward segments** are per-stage ``shard_map`` programs over
  the stage sub-mesh.  A non-final segment runs its block slice over the
  inter-stage activation carry (``models/unet.py`` staged ``__call__``);
  its backward *recomputes* the segment forward inside ``jax.vjp``
  (stage-granular remat — only the stage's input carry is stashed, never
  its interior activations).  Segments contain **no collectives**: the
  carry crosses the stage boundary in the model compute dtype (no
  widening), and all gradient traffic belongs to the stage update.
- **per-stage gradient sync + update** reuses the exact wire and fenced
  update of ``make_update_step``: gradients accumulate per replica
  (stacked ``[N_data, ...]`` so ``quantize_local`` keeps reference
  per-replica semantics across the program boundary), and the stage
  update runs the bucketed/fenced quantized collective + the ZeRO
  off/zero1/zero2 ladder **within the stage group**.  zero3's
  gather-on-demand is refused loudly (stage residency already divides
  params by S; composing the per-leaf gather with staged segments is a
  follow-on, see ROADMAP).
- **schedule**: GPipe two-phase round-robin.  Forward cycles ``t`` run
  stage ``s`` on microbatch ``t - s``; backward mirrors it.  Dispatch is
  asynchronous and the stages live on disjoint devices, so cycles
  genuinely overlap; the fill/drain bubble is ``(S-1)/(M+S-1)`` per
  phase (:func:`bubble_fraction`), measured — not guessed — by
  ``bench.py --pipeline-ab``.  1F1B is a follow-on knob: it reorders
  this host loop, nothing below changes.

``pipeline_stages=1`` **delegates** to the unstaged
``make_train_step`` — bit-identical by construction (same fenced update,
same wire bytes), and pinned numerically in tests/test_pipeline.py so
the refactor cannot drift the existing program baseline.

Tier: ``jax`` (analysis/tiers.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.parallel import partition
from ddlpc_tpu.parallel import shard_update as zero
from ddlpc_tpu.parallel.grad_sync import (
    sync_gradients,
    validate_scatter_compression,
)
from ddlpc_tpu.parallel.mesh import stage_meshes
from ddlpc_tpu.parallel.train_step import (
    TrainState,
    _apply_update_sharded,
    _apply_update_zero1,
    _fenced_update,
    _rounding_rng,
    loss_from_logits,
    make_train_step,
)
from ddlpc_tpu.utils.compat import shard_map

PyTree = Any


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """GPipe fill/drain bubble per phase: (S-1)/(M+S-1).  The model the
    measured column of ``bench.py --pipeline-ab`` is compared against."""
    s, m = int(n_stages), int(n_microbatches)
    if s < 1 or m < 1:
        raise ValueError(f"need S >= 1 and M >= 1, got S={s} M={m}")
    return (s - 1) / (m + s - 1)


def _subtree(params: PyTree, path: str):
    """Walk a "/"-joined module path into a nested param dict; None when
    absent (e.g. ``UpBlock_i/ConvTranspose_0`` under bilinear upsampling,
    a legitimately parameterless cut point)."""
    node = params
    for seg in path.split("/"):
        if not hasattr(node, "get"):
            return None
        node = node.get(seg)
        if node is None:
            return None
    return node


def _tree_bytes(tree: PyTree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        n = 1
        for d in leaf.shape:
            n *= int(d)
        total += n * jnp.dtype(leaf.dtype).itemsize
    return total


# ---------------------------------------------------------------------------
# stage plan


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """The resolved cut: which blocks (and which param-tree modules) each
    stage owns, plus the rule table every tree split reads."""

    block_names: Tuple[str, ...]  # model execution order, incl. 'head'
    assignment: Tuple[int, ...]  # per-block stage index, non-decreasing
    rules: Tuple[partition.StageRule, ...]  # over param-tree module names
    n_stages: int

    def stage_blocks(self, s: int) -> Tuple[str, ...]:
        return tuple(
            b for b, a in zip(self.block_names, self.assignment) if a == s
        )

    def split(self, tree: PyTree, prefix: str = "") -> List[PyTree]:
        return partition.split_tree_by_stage(
            self.rules, tree, self.n_stages, prefix
        )

    @staticmethod
    def merge(stage_trees: Sequence[PyTree]) -> PyTree:
        return partition.merge_stage_trees(stage_trees)


def build_stage_plan(model: nn.Module, params: PyTree, n_stages: int) -> StagePlan:
    """Cut the model's block list into ``n_stages`` contiguous groups by
    balanced per-block parameter bytes — the cut that minimizes the max
    per-stage share, i.e. maximizes the per-device HBM win the pipe axis
    exists for (obs/hbm.py prices it; the U-Net decoder is heavier than
    the encoder, so a naive halfway cut would not reach ~1/S)."""
    if not hasattr(model, "pipeline_block_names"):
        raise ValueError(
            f"{type(model).__name__} does not declare pipeline blocks "
            f"(pipeline_block_names/pipeline_block_modules) — staged "
            f"execution currently covers the U-Net family; see ROADMAP"
        )
    blocks = tuple(model.pipeline_block_names())
    modules = model.pipeline_block_modules()
    block_bytes = []
    for b in blocks:
        total = 0
        for m in modules[b]:
            sub = _subtree(params, m)
            if sub is not None:
                total += _tree_bytes(sub)
        block_bytes.append(total)
    assignment = partition.balanced_stage_assignment(block_bytes, n_stages)
    # The rule table speaks param-tree module names, not block names —
    # 'head' fans out to Conv_0 (+ detail heads).
    mod_names: List[str] = []
    mod_stage: List[int] = []
    for b, a in zip(blocks, assignment):
        for m in modules[b]:
            mod_names.append(m)
            mod_stage.append(a)
    rules = partition.stage_rules_for_blocks(mod_names, mod_stage)
    return StagePlan(blocks, tuple(assignment), rules, n_stages)


def stage_param_bytes(plan: StagePlan, params: PyTree) -> List[int]:
    """Per-stage parameter bytes under the plan — the numerator of the
    ``pipe=S`` HBM claim (params, grads and Adam moments all scale with
    it: 16·P_s bytes/device at fp32 off-layout vs 16·P unstaged)."""
    return [_tree_bytes(t) for t in plan.split(params)]


# ---------------------------------------------------------------------------
# opt-state split/merge (template + named-path fill)


def _named_map(tree: PyTree) -> Dict[str, Any]:
    return dict(partition.named_leaves(tree))


def split_opt_state(
    tx: optax.GradientTransformation,
    full_opt: PyTree,
    stage_params: Sequence[PyTree],
) -> List[PyTree]:
    """Split a canonical opt_state into per-stage opt_states: build each
    stage's template with ``tx.init(stage_params)`` (same optax chain →
    same outer structure, param-subtree inner structure) and fill every
    template leaf from the identically-named leaf of the full opt_state.
    Scalars (``count`` etc.) replicate into every stage — they advance in
    lockstep, so the merge takes stage 0's copy back."""
    full = _named_map(full_opt)
    outs: List[PyTree] = []
    for ps in stage_params:
        template = jax.eval_shape(tx.init, ps)

        def fill(path, leaf):
            name = partition.leaf_name("", path)
            if name not in full:
                raise ValueError(
                    f"opt_state leaf {name!r} of a stage template has no "
                    f"counterpart in the full opt_state — tx must not "
                    f"couple state across the param tree"
                )
            got = full[name]
            if tuple(got.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"opt_state leaf {name!r}: stage template shape "
                    f"{tuple(leaf.shape)} != full shape {tuple(got.shape)}"
                )
            return got

        outs.append(jax.tree_util.tree_map_with_path(fill, template))
    return outs


def merge_opt_state(
    tx: optax.GradientTransformation,
    full_params: PyTree,
    stage_opts: Sequence[PyTree],
) -> PyTree:
    """Inverse of :func:`split_opt_state`: fill the canonical
    ``tx.init(full_params)`` template from the stage opt_states (first
    stage that has the named leaf wins — scalars are lockstep-identical
    replicas; moment leaves exist in exactly one stage)."""
    maps = [_named_map(o) for o in stage_opts]
    template = jax.eval_shape(tx.init, full_params)

    def fill(path, leaf):
        name = partition.leaf_name("", path)
        for m in maps:
            if name in m:
                return m[name]
        raise ValueError(
            f"opt_state leaf {name!r} of the canonical template exists in "
            f"no stage opt_state — the stage plans disagree with tx"
        )

    return jax.tree_util.tree_map_with_path(fill, template)


# ---------------------------------------------------------------------------
# pipeline state


@dataclasses.dataclass
class PipelineState:
    """Per-stage :class:`TrainState` list, each resident on its stage
    sub-mesh (ZeRO-placed within the stage group when the level says so).
    NOT a pytree — stages live on disjoint device groups; host code moves
    between this and the canonical gathered :class:`TrainState` via the
    driver's ``init_state``/``canonical``."""

    stages: List[TrainState]

    @property
    def step(self):
        return self.stages[0].step


# ---------------------------------------------------------------------------
# the driver


class PipelineTrainStep:
    """Host-driven MPMD pipeline train step.

    ``init_state(full_state)`` splits + places a canonical TrainState;
    ``step(pstate, images, labels)`` runs one optimizer step over
    ``images [M, B, H, W, C]`` / ``labels [M, B, H, W]`` (M microbatches,
    B = global microbatch) and returns ``(pstate, metrics)`` with float
    metrics; ``canonical(pstate)`` gathers back to the layout checkpoints
    store — so a ``pipe=S, zeroN`` run round-trips into any other layout
    exactly like the ZeRO rungs do (tests/test_shard_update.py matrix).

    After every ``step`` the driver leaves ``last_schedule`` behind:
    executed vs idle (stage × cycle) slots of the round-robin it just
    ran, and their ratio as the MEASURED bubble fraction —
    ``bench.py --pipeline-ab`` tables it against the closed form.
    """

    def __init__(
        self,
        model: nn.Module,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        compression: CompressionConfig,
        n_microbatches: int,
        data_axis: str = "data",
        space_axis: str = "space",
        pipe_axis: str = "pipe",
        shard_update: str = "off",
        seed: int = 0,
    ):
        self.model, self.tx, self.compression = model, tx, compression
        self.data_axis, self.seed = data_axis, seed
        self.n_stages = int(mesh.shape.get(pipe_axis, 1))
        self.n_microbatches = max(int(n_microbatches), 1)
        level = zero.normalize_shard_update(shard_update)
        if self.n_stages <= 1:
            # Degenerate pipe=1: the unstaged builder IS the program —
            # same fenced update, same wire bytes, bit-identical
            # (pinned in tests/test_pipeline.py).
            self._level = level
            self._mesh = mesh
            self._delegate_build = lambda layout: make_train_step(
                model, tx, mesh, compression, data_axis=data_axis,
                seed=seed, shard_update=level,
                param_avals=layout.param_avals,
            )
            return
        if space_axis in mesh.shape and mesh.shape[space_axis] > 1:
            raise ValueError(
                "pipeline stages × space sharding of the full model is not "
                "wired yet: segment shard_map programs do not emit the "
                "per-conv halo exchanges the GSPMD path gets for free "
                "(parallel/halo.py composes with staged execution at the "
                "carry level — tests/test_pipeline.py — full-model wiring "
                "is a ROADMAP follow-on)"
            )
        if level == "zero3":
            raise ValueError(
                "shard_update='zero3' does not compose with pipeline "
                "stages yet: stage residency already divides params by S; "
                "per-leaf gather-on-demand inside staged segments is a "
                "ROADMAP follow-on (use off/zero1/zero2 within stages)"
            )
        if level in ("zero2",):
            validate_scatter_compression(compression)
        self._level = level
        self._meshes = stage_meshes(mesh, pipe_axis)
        if len(self._meshes) != self.n_stages:
            raise AssertionError("stage_meshes disagrees with pipe axis")
        self._n_data = self._meshes[0].shape[data_axis]
        self.plan: Optional[StagePlan] = None  # built on first init_state
        self._built = False

    # -- canonical <-> placed ------------------------------------------------

    def init_state(self, full_state: TrainState) -> PipelineState:
        if self.n_stages <= 1:
            layout = self._layout_for(full_state)
            self._mono = self._delegate_build(layout)
            self._mono_layout = layout
            return PipelineState([layout.place(full_state)])
        if self.plan is None:
            self.plan = build_stage_plan(
                self.model, full_state.params, self.n_stages
            )
        p_split = self.plan.split(full_state.params)
        s_split = self.plan.split(full_state.batch_stats)
        o_split = split_opt_state(self.tx, full_state.opt_state, p_split)
        stages: List[TrainState] = []
        self._layouts: List[Optional[zero.StateLayout]] = []
        for s in range(self.n_stages):
            st = TrainState(
                step=full_state.step,
                params=p_split[s],
                batch_stats=s_split[s],
                opt_state=o_split[s],
            )
            st = jax.device_get(st)  # host detour: source may be any mesh
            if self._level == "off" or self._n_data <= 1:
                repl = NamedSharding(self._meshes[s], P())
                st = jax.tree.map(lambda x: jax.device_put(x, repl), st)
                self._layouts.append(None)
            else:
                layout = zero.StateLayout(
                    self._level, self.tx, st, self._meshes[s], self.data_axis
                )
                st = layout.place(st)
                self._layouts.append(layout)
            stages.append(st)
        self._p_split, self._s_split = p_split, s_split
        if not self._built:
            self._build_programs(p_split)
            self._built = True
        return PipelineState(stages)

    def carry_avals(self, image_shape, image_dtype=jnp.float32) -> List[PyTree]:
        """Abstract inter-stage carry avals for one microbatch, per stage
        boundary (S-1 entries) — what one activation send moves, and what
        the GPipe input stash holds M of
        (``obs.hbm.pipeline_carry_stash_bytes`` prices it).  Requires
        ``init_state`` to have run (the stage plan fixes the cut)."""
        if self.n_stages <= 1:
            return []
        if self.plan is None:
            raise ValueError("carry_avals needs init_state first (no plan)")
        out: List[PyTree] = []
        cin: Any = jax.ShapeDtypeStruct(tuple(image_shape), image_dtype)
        for s in range(self.n_stages - 1):
            # Through the real stage program (not a bare apply): sync-BN
            # pmeans over the data axis, which only exists inside the
            # stage shard_map.
            cin, _ = jax.eval_shape(
                self._fwd[s], self._p_split[s], self._s_split[s], cin
            )
            out.append(cin)
        return out

    def canonical(self, pstate: PipelineState) -> TrainState:
        if self.n_stages <= 1:
            return self._mono_layout.canonical(pstate.stages[0])
        gathered = []
        for st, layout in zip(pstate.stages, self._layouts):
            gathered.append(
                jax.device_get(layout.canonical(st) if layout else st)
            )
        params = StagePlan.merge([g.params for g in gathered])
        stats = StagePlan.merge([g.batch_stats for g in gathered])
        opt = merge_opt_state(self.tx, params, [g.opt_state for g in gathered])
        return TrainState(
            step=gathered[0].step, params=params,
            batch_stats=stats, opt_state=opt,
        )

    def _layout_for(self, full_state: TrainState) -> zero.StateLayout:
        mode = "replicated" if self._level == "off" else self._level
        return zero.StateLayout(
            mode, self.tx, full_state, self._mesh, self.data_axis
        )

    # -- per-stage compiled programs ----------------------------------------

    def _build_programs(self, p_split) -> None:
        S, model, comp = self.n_stages, self.model, self.compression
        data_axis, N, M = self.data_axis, self._n_data, self.n_microbatches
        self._fwd: List[Callable] = []
        self._bwd: List[Callable] = []
        self._upd: List[Callable] = []
        self._gacc_init: List[Callable] = []

        def apply_blocks(params, stats, x, carry, blocks):
            out, updates = model.apply(
                {"params": params, "batch_stats": stats},
                x, train=True, mutable=["batch_stats"],
                blocks=blocks, carry=carry,
            )
            return out, updates["batch_stats"]

        for s in range(S):
            mesh_s = self._meshes[s]
            blocks = self.plan.stage_blocks(s)
            first, last = s == 0, s == S - 1

            def make_fwd(blocks=blocks, first=first, mesh_s=mesh_s):
                def body(params, stats, cin):
                    x = cin if first else cin["x"]
                    carry = None if first else cin
                    out, new_stats = apply_blocks(params, stats, x, carry, blocks)
                    return out, new_stats

                return jax.jit(shard_map(
                    body, mesh=mesh_s,
                    in_specs=(P(), P(), P(data_axis)),
                    out_specs=(P(data_axis), P()),
                    check=False,
                ))

            def make_bwd(blocks=blocks, first=first, mesh_s=mesh_s):
                # Stage-granular remat: re-run the segment forward inside
                # vjp with the STASHED input stats (the stats this
                # microbatch's forward consumed), discard the recomputed
                # stats, and pull (d_params, d_carry_in) through.  Stage 0
                # skips the carry cotangent (nothing upstream wants it).
                def body(params, stats, cin, dout, gacc):
                    x = cin if first else cin["x"]
                    carry = None if first else cin

                    def seg_p(p):
                        return apply_blocks(p, stats, x, carry, blocks)[0]

                    def seg_pc(p, c):
                        return apply_blocks(p, stats, c["x"], c, blocks)[0]

                    if first:
                        _, vjp_fn = jax.vjp(seg_p, params)
                        (gp,) = vjp_fn(dout)
                        dcin = jnp.zeros((), jnp.float32)  # unused stub
                    else:
                        _, vjp_fn = jax.vjp(seg_pc, params, cin)
                        gp, dcin = vjp_fn(dout)
                    gacc = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32)[None], gacc, gp
                    )
                    return dcin, gacc

                dcin_spec = P() if first else P(data_axis)
                return jax.jit(
                    shard_map(
                        body, mesh=mesh_s,
                        in_specs=(P(), P(), P(data_axis), P(data_axis),
                                  P(data_axis)),
                        out_specs=(dcin_spec, P(data_axis)),
                        check=False,
                    ),
                    donate_argnums=(4,),
                )

            def make_loss_bwd(blocks=blocks, first=first, mesh_s=mesh_s):
                # The final stage's forward, loss and backward are ONE
                # program: loss math is loss_from_logits — byte-identical
                # to the monolithic builders' tail.  Per-replica loss/acc
                # leave stacked over the data axis (host averages equal
                # shards) so the segment stays collective-free.
                def body(params, stats, cin, labels, gacc):
                    x = cin if first else cin["x"]
                    carry = None if first else cin

                    def loss_fn(p, c):
                        xx = x if first else c["x"]
                        cc = None if first else c
                        logits, new_stats = apply_blocks(p, stats, xx, cc, blocks)
                        loss, acc = loss_from_logits(
                            model, logits, labels, train=True
                        )
                        return loss, (new_stats, acc)

                    if first:  # S==1 never lands here; guard anyway
                        (loss, (new_stats, acc)), gp = jax.value_and_grad(
                            lambda p: loss_fn(p, None), has_aux=True
                        )(params)
                        dcin = jnp.zeros((), jnp.float32)
                    else:
                        (loss, (new_stats, acc)), (gp, dcin) = (
                            jax.value_and_grad(
                                loss_fn, argnums=(0, 1), has_aux=True
                            )(params, carry)
                        )
                    gacc = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32)[None], gacc, gp
                    )
                    return loss[None], acc[None], dcin, new_stats, gacc

                dcin_spec = P() if first else P(data_axis)
                return jax.jit(
                    shard_map(
                        body, mesh=mesh_s,
                        in_specs=(P(), P(), P(data_axis), P(data_axis),
                                  P(data_axis)),
                        out_specs=(P(data_axis), P(data_axis), dcin_spec,
                                   P(), P(data_axis)),
                        check=False,
                    ),
                    donate_argnums=(4,),
                )

            def make_upd(mesh_s=mesh_s, level=self._level):
                axis_size = mesh_s.shape[data_axis]
                lvl = "off" if axis_size <= 1 else level

                def body(params, opt_state, gacc, stats, step):
                    # gacc arrives as this replica's [1, ...] sum over M
                    # microbatch backward passes — squeeze + /M is the
                    # monolithic _accumulate_grads mean, then the EXACT
                    # make_update_step wire/update per ZeRO level.
                    grads = jax.tree.map(lambda a: a[0] / M, gacc)
                    rng = _rounding_rng(comp, self.seed, step)
                    if lvl == "zero2":
                        params, opt_state, norm = _apply_update_sharded(
                            self.tx, params, opt_state, grads,
                            data_axis, axis_size, comp, rng,
                        )
                        grad_sq = jnp.square(norm)
                    elif lvl == "zero1":
                        params, opt_state, norm = _apply_update_zero1(
                            self.tx, params, opt_state, grads,
                            data_axis, axis_size, comp, rng,
                        )
                        grad_sq = jnp.square(norm)
                    else:
                        grads = sync_gradients(
                            grads, data_axis, comp,
                            axis_size=axis_size, key=rng,
                        )
                        params, opt_state = _fenced_update(
                            self.tx, grads, opt_state, params
                        )
                        grad_sq = jnp.square(optax.global_norm(grads))
                    # End-of-step stats sync, the monolithic step's pmean.
                    stats = jax.tree.map(
                        lambda v: lax.pmean(v, data_axis), stats
                    )
                    return params, opt_state, stats, grad_sq, step + 1

                def stepper(params, opt_state, gacc, stats, step):
                    if lvl == "off":
                        opt_specs: PyTree = P()
                        param_specs: PyTree = P()
                    else:
                        opt_specs = zero.opt_partition_specs(
                            self.tx, params, lvl, data_axis
                        )
                        param_specs = P()
                    sharded = shard_map(
                        body, mesh=mesh_s,
                        in_specs=(param_specs, opt_specs, P(data_axis),
                                  P(), P()),
                        out_specs=(param_specs, opt_specs, P(), P(), P()),
                        check=False,
                    )
                    return sharded(params, opt_state, gacc, stats, step)

                return jax.jit(stepper, donate_argnums=(0, 1, 2))

            def make_gacc_init(p_s=p_split[s], mesh_s=mesh_s):
                sh = jax.tree.map(
                    lambda _: NamedSharding(mesh_s, P(data_axis)), p_s
                )

                def zeros():
                    return jax.tree.map(
                        lambda a: jnp.zeros((N,) + tuple(a.shape), jnp.float32),
                        p_s,
                    )

                return jax.jit(zeros, out_shardings=sh)

            self._fwd.append(None if last else make_fwd())
            self._bwd.append(make_loss_bwd() if last else make_bwd())
            self._upd.append(make_upd())
            self._gacc_init.append(make_gacc_init())

    # -- transfers -----------------------------------------------------------

    def _to_stage(self, tree: PyTree, s: int) -> PyTree:
        """Move an activation carry (or cotangent) onto stage ``s``'s
        sub-mesh, batch axis over data — the explicit inter-stage send.
        jax.device_put across disjoint device groups dispatches
        asynchronously, which is what lets forward cycles overlap."""
        sh = NamedSharding(self._meshes[s], P(self.data_axis))
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)

    # -- the step ------------------------------------------------------------

    def step(
        self, pstate: PipelineState, images, labels
    ) -> Tuple[PipelineState, Dict[str, float]]:
        if self.n_stages <= 1:
            self.last_schedule = {
                "executed_slots": self.n_microbatches,
                "idle_slots": 0,
                "measured_bubble": 0.0,
            }
            new_state, metrics = self._mono(pstate.stages[0], images, labels)
            return (
                PipelineState([new_state]),
                {k: float(np.asarray(jax.device_get(v)))
                 for k, v in metrics.items()},
            )
        S, M = self.n_stages, self.n_microbatches
        executed = 0  # dispatched (stage, cycle) slots — see last_schedule
        if images.shape[0] != M:
            raise ValueError(
                f"images leading dim {images.shape[0]} != "
                f"n_microbatches={M}"
            )
        last = S - 1
        # Input stashes: stage s's microbatch-m input carry and the stats
        # snapshot its forward consumed (the backward recompute needs it).
        cin: List[List[Any]] = [[None] * M for _ in range(S)]
        stats_in: List[List[Any]] = [[None] * M for _ in range(S)]
        stats = [st.batch_stats for st in pstate.stages]
        for m in range(M):
            cin[0][m] = self._to_stage(jnp.asarray(images[m]), 0)
        labels_dev = [
            self._to_stage(jnp.asarray(labels[m]), last) for m in range(M)
        ]
        gacc = [init() for init in self._gacc_init]

        # Forward phase: stages 0..S-2 (the last stage folds its forward
        # into the loss/backward program).  Cycle t runs stage s on
        # microbatch t-s; descending s so a cycle consumes carries the
        # previous cycle stashed — ≤S-1 concurrent programs on disjoint
        # sub-meshes per cycle.
        for t in range(M + S - 2):
            for s in range(min(S - 2, t), -1, -1):
                m = t - s
                if not 0 <= m < M:
                    continue
                stats_in[s][m] = stats[s]
                out, stats[s] = self._fwd[s](
                    pstate.stages[s].params, stats[s], cin[s][m]
                )
                cin[s + 1][m] = self._to_stage(out, s + 1)
                executed += 1

        # Backward phase: stage s at cycle t runs microbatch t-(S-1-s),
        # consuming the cotangent stage s+1 produced last cycle.
        dstash: List[List[Any]] = [[None] * M for _ in range(S)]
        losses, accs = [], []
        for t in range(M + S - 1):
            for s in range(S - 1, -1, -1):
                m = t - (last - s)
                if not 0 <= m < M:
                    continue
                if s == last:
                    stats_in[s][m] = stats[s]
                    loss_m, acc_m, dcin, stats[s], gacc[s] = self._bwd[s](
                        pstate.stages[s].params, stats_in[s][m],
                        cin[s][m], labels_dev[m], gacc[s],
                    )
                    losses.append(loss_m)
                    accs.append(acc_m)
                else:
                    dcin, gacc[s] = self._bwd[s](
                        pstate.stages[s].params, stats_in[s][m],
                        cin[s][m], dstash[s][m], gacc[s],
                    )
                cin[s][m] = None  # free the carry stash
                executed += 1
                if s > 0:
                    dstash[s - 1][m] = self._to_stage(dcin, s - 1)

        # Schedule occupancy, counted off the loops that actually ran —
        # the MEASURED bubble (bench.py --pipeline-ab): idle fraction of
        # the (stage × cycle) grid the two-phase round-robin spans.  On
        # the single-host CPU audit topology wall-clock carries no idle
        # signal (every virtual device shares the same cores), so this is
        # the observable that catches a schedule bug — e.g. a fill/drain
        # mistake dispatches fewer slots per cycle and the fraction jumps,
        # while the closed form (:func:`bubble_fraction`) stays put.
        slots = (S - 1) * (M + S - 2) + S * (M + S - 1)
        self.last_schedule = {
            "executed_slots": executed,
            "idle_slots": slots - executed,
            "measured_bubble": round((slots - executed) / slots, 4),
        }

        # Per-stage update: the quantized bucketed fenced wire + ZeRO
        # ladder within each stage group, dispatched concurrently.
        new_stages, grad_sqs = [], []
        for s in range(S):
            st = pstate.stages[s]
            params, opt, new_stats, grad_sq, step = self._upd[s](
                st.params, st.opt_state, gacc[s], stats[s], st.step
            )
            new_stages.append(TrainState(
                step=step, params=params,
                batch_stats=new_stats, opt_state=opt,
            ))
            grad_sqs.append(grad_sq)
        metrics = {
            "loss": float(np.mean([np.asarray(v).mean() for v in losses])),
            "pixel_acc": float(np.mean([np.asarray(v).mean() for v in accs])),
            "grad_norm": float(np.sqrt(
                np.sum([np.asarray(v) for v in grad_sqs])
            )),
        }
        return PipelineState(new_stages), metrics


def make_pipeline_train_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    compression: CompressionConfig,
    n_microbatches: int,
    data_axis: str = "data",
    space_axis: str = "space",
    pipe_axis: str = "pipe",
    shard_update: str = "off",
    seed: int = 0,
) -> PipelineTrainStep:
    """Build the pipeline driver for ``mesh`` (staged iff it has a
    ``pipe`` axis > 1 — ``make_mesh`` adds one for
    ``ParallelConfig.pipeline_stages > 1``).  See
    :class:`PipelineTrainStep` for the driver API and the module
    docstring for schedule/memory semantics."""
    return PipelineTrainStep(
        model, tx, mesh, compression, n_microbatches,
        data_axis=data_axis, space_axis=space_axis, pipe_axis=pipe_axis,
        shard_update=shard_update, seed=seed,
    )
