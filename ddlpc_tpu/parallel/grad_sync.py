"""Synchronous gradient synchronization with optional lossy compression.

This is the TPU-native replacement for the reference's whole L4 protocol
(кластер.py:255-557): workers quantize accumulated grads and send to the
server; the server averages, re-quantizes the average, broadcasts; everyone
(server included, via its self-application block кластер.py:402-433) steps on
the *same* dequantized gradient.

Semantics preserved:
- optional per-replica quantization before the reduce (the worker wire);
- exact mean across all replicas — fixing the reference's "crooked averaging
  … (fix!)" loop that over-divides earlier contributions and divides by the
  worker count instead of the replica count (кластер.py:268-321, SURVEY §2.8d);
- optional re-quantization of the mean, so every replica applies a
  bit-identical update (SPMD + deterministic psum already guarantees
  identical values; re-quantization reproduces the reference's *information
  loss*, not its mechanism).

Runs inside shard_map over the ``data`` mesh axis: `lax.pmean` lowers to one
fused XLA all-reduce over ICI/DCN instead of N sequential pickled TCP
round-trips.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.ops.quantize import fake_quantize

PyTree = Any


def sync_gradients(
    grads: PyTree, axis_name: str, compression: CompressionConfig
) -> PyTree:
    """All-reduce-mean local gradients across ``axis_name``.

    Call inside shard_map/pmap.  With compression.mode='none' this is a plain
    pmean; otherwise the codec's information loss is injected at the same
    points the reference loses it (client send: quantize_local; server
    rebroadcast: quantize_mean).
    """
    if compression.quantize_local:
        grads = fake_quantize(grads, compression)
    grads = lax.pmean(grads, axis_name)
    if compression.quantize_mean:
        grads = fake_quantize(grads, compression)
    return grads
