"""Synchronous gradient synchronization with optional lossy compression.

This is the TPU-native replacement for the reference's whole L4 protocol
(кластер.py:255-557): workers quantize accumulated grads and send to the
server; the server averages, re-quantizes the average, broadcasts; everyone
(server included, via its self-application block кластер.py:402-433) steps on
the *same* dequantized gradient.

Semantics preserved:
- optional per-replica quantization before the reduce (the worker wire);
- exact mean across all replicas — fixing the reference's "crooked averaging
  … (fix!)" loop that over-divides earlier contributions and divides by the
  worker count instead of the replica count (кластер.py:268-321, SURVEY §2.8d);
- optional re-quantization of the mean, so every replica applies a
  bit-identical update (SPMD + deterministic psum already guarantees
  identical values; re-quantization reproduces the reference's *information
  loss*, not its mechanism).

Runs inside shard_map over the ``data`` mesh axis: `lax.pmean` lowers to one
fused XLA all-reduce over ICI/DCN instead of N sequential pickled TCP
round-trips.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.ops.quantize import (
    _leaf_keys,
    fake_quantize,
    global_absmax,
    levels_for,
    quantize_with_scale,
    rounding_key,
    safe_divisor,
    snap_to_lattice,
)
from ddlpc_tpu.parallel.bucketing import bucket_index_groups
from ddlpc_tpu.parallel.compressed_allreduce import wire_dtype
from ddlpc_tpu.parallel.shard_update import chunk_leaf, local_chunk

PyTree = Any


def resolve_codec_backend(compression: CompressionConfig):
    """The fake-quantize implementation for the simulate transport: the XLA
    tree transform, or the fused Pallas kernel (interpreted off-TPU so the
    CPU test meshes exercise the same code path)."""
    if compression.codec_backend == "pallas":
        from ddlpc_tpu.ops.pallas_quantize import (
            default_interpret,
            fake_quantize_pallas,
        )

        return functools.partial(
            fake_quantize_pallas, interpret=default_interpret()
        )
    if compression.codec_backend == "xla":
        return fake_quantize
    raise ValueError(
        f"unknown codec_backend {compression.codec_backend!r} "
        "(expected 'xla' or 'pallas')"
    )


def simulate_wire_dtype(
    axis_size: Optional[int], compression: CompressionConfig
):
    """The narrow dtype the simulate transport puts on the wire for this
    config, or None when the exact-fp32 fake-quantize path must stay.

    The fused collective sums LATTICE values — integers in [-levels,
    levels] — so the reduce is exact (and therefore bit-identical across
    program layouts, reduction order included) iff every partial sum is
    representable on the wire: int8/int16 per
    ``compressed_allreduce.wire_dtype``'s bound for the int8 codec, f16
    while ``axis_size·levels ≤ 2048`` for the fp16 codec (every integer up
    to 2048 is exact in fp16; above it the ulp is 2 and sums would round).
    mode='none' has no codec and quantize_local=False has no pre-reduce
    lattice to ship — both keep the fp32 wire.  The program auditor's
    declared wire dtype (analysis/program.py) mirrors this function
    exactly; the HLO dtype-flow contract is what proves the declaration.
    """
    if (
        axis_size is None
        or compression.mode == "none"
        or not compression.quantize_local
        or compression.transport != "simulate"
    ):
        return None
    levels = levels_for(compression)
    if compression.mode == "int8":
        try:
            return wire_dtype(axis_size, levels)
        except ValueError:
            return None
    if axis_size * levels <= 2048:
        return jnp.float16
    return None


def grad_bucket_groups(tree: PyTree, bucket_mb: float):
    """Per-bucket leaf-index lists over ``tree``'s flatten order — a pure
    function of the leaf shapes (parallel/bucketing.py), so the replicated,
    ZeRO-1 and GSPMD step builders all derive the identical partition and
    the auditor's census counts the same buckets in each layout."""
    leaves = jax.tree_util.tree_leaves(tree)
    sizes = [int(l.size) * jnp.dtype(l.dtype).itemsize for l in leaves]
    return bucket_index_groups(sizes, bucket_mb)


def _bucketed(tree: PyTree, bucket_mb: float, key, sync_one) -> PyTree:
    """Run ``sync_one(subtree, key)`` once per size-targeted bucket and
    reassemble.  One bucket (bucket_mb=0, or a target larger than the whole
    tree) short-circuits to a single call on the ORIGINAL tree with the
    ORIGINAL key — trace-identical to the pre-bucketing program, which is
    what keeps the degenerate case bit-identical.  With several buckets
    each gets ``fold_in(key, bucket_index)`` (before the local/mean split,
    so buckets draw independent noise at both loss points) and its own
    scales — the partition is the unit of codec loss."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups = grad_bucket_groups(tree, bucket_mb)
    if len(groups) == 1:
        return sync_one(tree, key)
    out: list = [None] * len(leaves)
    for b, idxs in enumerate(groups):
        bkey = None if key is None else jax.random.fold_in(key, b)
        part = sync_one([leaves[i] for i in idxs], bkey)
        for i, v in zip(idxs, jax.tree_util.tree_leaves(part)):
            out[i] = v
    return jax.tree_util.tree_unflatten(treedef, out)


def _fenced_wire_encode(
    grads: PyTree, compression, safe, levels: float, wire, key
) -> PyTree:
    """Fenced encode-to-wire: barrier(grads) → snap to the lattice against
    the shared (pmax'd) scale → cast to the wire dtype → barrier.  The
    fences isolate exactly the codec's elementwise region, same cut points
    as apply_codec_fenced — the downstream DEQUANTIZE is deliberately not
    here (it stays unfenced so XLA can fuse the single scalar multiply
    into the collective's consumer; one multiply cannot FMA-contract, so
    program layouts cannot round it differently)."""
    grads = lax.optimization_barrier(grads)
    if compression.codec_backend == "pallas":
        from ddlpc_tpu.ops.pallas_quantize import (
            default_interpret,
            encode_to_wire_pallas,
        )

        q = encode_to_wire_pallas(
            grads, compression, safe, wire,
            key=key, interpret=default_interpret(),
        )
    else:
        q = jax.tree.map(
            lambda g, k: quantize_with_scale(g, safe, levels, key=k).astype(
                wire
            ),
            grads,
            _leaf_keys(grads, key),
        )
    return lax.optimization_barrier(q)


def _wire_decode(tree: PyTree, inv, compression) -> PyTree:
    """Dequantize a summed wire tree: one multiply by the runtime scalar
    ``inv = scale / (levels · axis_size)`` — quantize.decode's formula with
    the mean division folded into the same single rounding."""
    if compression.codec_backend == "pallas":
        from ddlpc_tpu.ops.pallas_quantize import (
            decode_from_wire_pallas,
            default_interpret,
        )

        return decode_from_wire_pallas(
            tree, inv, interpret=default_interpret()
        )
    return jax.tree.map(lambda q: q.astype(jnp.float32) * inv, tree)


def _fused_allreduce_mean(
    grads: PyTree, axis_name, compression, axis_size, local_key, wire
) -> PyTree:
    """quantize_local's loss point with the NARROW dtype on the wire: the
    all-reduce operand is the int8/int16/f16 lattice, not fp32.  The scale
    is shared across replicas (lax.pmax of the per-replica abs-maxes — the
    ring transport's convention) so the integer row sums dequantize with
    one global scalar; see docs/QUANTIZATION.md "True integer wire" for
    where this is bit-identical and where the shared scale is a declared,
    test-pinned deviation from the per-replica fake-quantize reference."""
    scale = lax.pmax(global_absmax(grads), axis_name)
    safe = safe_divisor(scale)
    levels = float(levels_for(compression))
    q = _fenced_wire_encode(grads, compression, safe, levels, wire, local_key)
    summed = lax.psum(q, axis_name)
    inv = scale / (levels * axis_size)
    return _wire_decode(summed, inv, compression)


def _fused_scatter_mean(
    grads: PyTree, axis_name, compression, axis_size, local_key, wire
) -> PyTree:
    """Reduce-scatter spelling of :func:`_fused_allreduce_mean`: encode the
    FULL leaves (identical call to the replicated path — the precondition
    for bit-identity), chunk the quantized [N, K] layout, psum_scatter the
    narrow rows (integer partial sums are exact, so row r's sum equals the
    corresponding elements of the replicated psum bit-for-bit), and
    dequantize only the local [1, K] shard."""
    scale = lax.pmax(global_absmax(grads), axis_name)
    safe = safe_divisor(scale)
    levels = float(levels_for(compression))
    q = _fenced_wire_encode(grads, compression, safe, levels, wire, local_key)
    summed = jax.tree.map(
        lambda qi: lax.psum_scatter(
            chunk_leaf(qi, axis_size), axis_name,
            scatter_dimension=0, tiled=True,
        ),
        q,
    )
    inv = scale / (levels * axis_size)
    return _wire_decode(summed, inv, compression)


def sync_gradients(
    grads: PyTree,
    axis_name: str,
    compression: CompressionConfig,
    axis_size: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> PyTree:
    """All-reduce-mean local gradients across ``axis_name``.

    Call inside shard_map/pmap.  With compression.mode='none' this is a plain
    pmean; otherwise the codec's information loss is injected at the same
    points the reference loses it (client send: quantize_local; server
    rebroadcast: quantize_mean).  When the lattice sums fit the narrow
    dtype (:func:`simulate_wire_dtype`), quantize_local FUSES into the
    collective: the all-reduce operand is the int8/f16 lattice itself —
    the quantized bits are what actually crosses the wire — instead of
    fp32 with the loss simulated around it.  ``compression.bucket_mb``
    splits the tree into size-targeted buckets, each synced by its own
    fused collective (parallel/bucketing.py).

    ``compression.transport='ring'`` swaps the fp32 pmean for the
    byte-compressed ppermute ring (compressed_allreduce.py), which needs the
    static ``axis_size`` of the mesh axis.

    ``key`` drives stochastic rounding (compression.rounding='stochastic');
    all replicas must pass the same key (the step builders derive it from
    the replicated step counter), which keeps the mean-requantization
    bit-identical across replicas.
    """
    if compression.transport not in ("simulate", "ring"):
        raise ValueError(
            f"unknown compression transport {compression.transport!r} "
            "(expected 'simulate' or 'ring')"
        )
    # Validate codec_backend up front on every path: the ring inlines its own
    # formula (backend-independent), but a typo'd backend must not be
    # silently accepted on one transport and rejected on the other.
    fq = resolve_codec_backend(compression)
    if compression.transport == "ring" and compression.mode != "none":
        if axis_size is None:
            raise ValueError(
                "transport='ring' needs the static axis_size (the step "
                "builders pass mesh.shape[data_axis])"
            )
        if compression.bucket_mb > 0:
            raise ValueError(
                "bucket_mb composes only with transport='simulate' — the "
                "ring's flatten/concat transport is whole-tree by "
                "construction (one concatenated wire buffer per sync)"
            )
        if not (compression.quantize_local and compression.quantize_mean):
            raise ValueError(
                "transport='ring' quantizes at both loss points by "
                "construction (integer wire sums + quantized gather hops); "
                "quantize_local/quantize_mean=False ablations need "
                "transport='simulate'"
            )
        from ddlpc_tpu.parallel.compressed_allreduce import (
            ring_allreduce_mean_quantized,
        )

        return ring_allreduce_mean_quantized(
            grads, axis_name, axis_size, compression, key=key
        )
    if compression.mode != "none":
        key = rounding_key(compression, key)
    return _bucketed(
        grads,
        compression.bucket_mb,
        key,
        lambda t, k: _sync_tree(t, axis_name, compression, axis_size, k, fq),
    )


def _sync_tree(grads, axis_name, compression, axis_size, key, fq) -> PyTree:
    """One bucket's all-reduce-mean (the whole tree when bucket_mb=0)."""
    local_key = mean_key = None
    if key is not None:
        local_key, mean_key = jax.random.split(key)
        # Decorrelate the LOCAL rounding noise across replicas: per-replica
        # gradients are highly correlated, so a shared draw would make the
        # rounding errors common-mode and survive the pmean at full-step
        # size instead of averaging down ~1/√N.  The MEAN key must stay
        # shared — every replica requantizes the identical mean and must
        # make identical decisions.
        local_key = jax.random.fold_in(local_key, lax.axis_index(axis_name))
    wire = simulate_wire_dtype(axis_size, compression)
    if wire is not None:
        grads = _fused_allreduce_mean(
            grads, axis_name, compression, axis_size, local_key, wire
        )
    else:
        if compression.quantize_local:
            grads = apply_codec_fenced(fq, grads, compression, key=local_key)
        grads = lax.pmean(grads, axis_name)
    if compression.quantize_mean:
        grads = apply_codec_fenced(fq, grads, compression, key=mean_key)
    return grads


def apply_codec_fenced(fq, grads: PyTree, compression, key=None) -> PyTree:
    """Run a fake-quantize stage inside ``lax.optimization_barrier`` fences.

    The barriers pin the codec's elementwise chain (scale divide, lattice
    snap, cast, dequantize) into an isolated fusion region: without them
    XLA fuses it into the surrounding collectives, and the replicated and
    sharded-update programs then round the SAME codec arithmetic
    differently (1-ulp FMA/fusion drift — the same effect documented at
    train_step._fenced_update, observed on both the shard_map and GSPMD
    paths).  Every step variant quantizes through this wrapper so the
    codec's bits cannot depend on which program surrounds it."""
    if compression.mode == "none":
        return fq(grads, compression, key=key)
    grads = lax.optimization_barrier(grads)
    return lax.optimization_barrier(fq(grads, compression, key=key))


def apply_codec_fenced_bucketed(fq, grads: PyTree, compression, key=None):
    """Bucketed spelling of :func:`apply_codec_fenced` for step builders
    with no explicit collective of their own (GSPMD: the partitioner owns
    the wire) — same per-bucket key schedule and per-bucket scales as the
    bucketed syncs, so the GSPMD codec loss matches the shard_map layouts
    bucket-for-bucket.  One bucket degenerates to apply_codec_fenced on
    the original tree."""
    return _bucketed(
        grads,
        compression.bucket_mb,
        key,
        lambda t, k: apply_codec_fenced(fq, t, compression, key=k),
    )


def validate_scatter_compression(compression: CompressionConfig) -> None:
    """Reject codec combinations the sharded update cannot reproduce
    bit-identically (shared by the step builders, for a build-time error,
    and sync_gradients_scatter, so the invariant cannot be bypassed).
    ``shard_update.resolve_shard_update``'s 'auto' avoids both."""
    if compression.transport not in ("simulate", "ring"):
        raise ValueError(
            f"unknown compression transport {compression.transport!r} "
            "(expected 'simulate' or 'ring')"
        )
    if compression.transport == "ring" and compression.mode != "none":
        raise ValueError(
            "sharded update composes only with transport='simulate' — "
            "transport='ring' owns its own full-tree quantized collective "
            "(set shard_update='off' to keep the ring)"
        )
    if (
        compression.mode != "none"
        and compression.quantize_mean
        and compression.codec_backend == "pallas"
    ):
        raise ValueError(
            "sharded update cannot reproduce the pallas mean-stage codec "
            "bit-identically (hardware-PRNG noise cannot be sliced to a "
            "shard) — use codec_backend='xla' or shard_update='off'"
        )


def sync_gradients_scatter(
    grads: PyTree,
    axis_name: str,
    compression: CompressionConfig,
    axis_size: int,
    key: Optional[jax.Array] = None,
) -> PyTree:
    """Reduce-scatter variant of :func:`sync_gradients` for the ZeRO-1
    sharded update (shard_update.py): instead of every replica receiving
    the full codec-processed mean, replica ``r`` receives ONLY its ``[1, K]``
    chunk of each leaf (chunk layout per ``shard_update.chunk_leaf``) —
    same wire volume as the all-reduce's reduce-scatter half, 1/N of the
    post-reduce arithmetic and memory per replica.

    Codec loss points map exactly onto :func:`sync_gradients` and are
    BIT-IDENTICAL per element to the replicated path (test-pinned):

    - ``quantize_local`` runs on the full per-replica gradients *before*
      the scatter — identical tensors, identical call.
    - ``quantize_mean`` runs on each replica's chunk of the mean with the
      GLOBAL scale (``lax.pmax`` of the per-chunk abs-maxes reproduces the
      whole-model max exactly — max is associative) and, for stochastic
      rounding, the replica's slice of the full leaf's threefry noise
      field (drawn at full shape from the shared mean key, then chunked —
      a shard-shaped draw would decide differently than the replicated
      path).  The scattered sum itself is bit-identical to ``psum`` on
      XLA's backends (both accumulate in ring order; pinned by the
      shard-vs-replicated identity tests).

    ``transport='ring'`` and the pallas mean-stage are rejected — see
    ``shard_update.resolve_shard_update`` for why they cannot compose.
    """
    validate_scatter_compression(compression)
    fq = resolve_codec_backend(compression)
    if compression.mode != "none":
        key = rounding_key(compression, key)
    return _bucketed(
        grads,
        compression.bucket_mb,
        key,
        lambda t, k: _scatter_tree(
            t, axis_name, compression, axis_size, k, fq
        ),
    )


def _scatter_tree(grads, axis_name, compression, axis_size, key, fq):
    """One bucket's reduce-scatter-mean (the whole tree when bucket_mb=0)."""
    local_key = mean_key = None
    if key is not None:
        local_key, mean_key = jax.random.split(key)
        # Same decorrelation as sync_gradients: local noise per replica,
        # mean noise shared (every replica slices the same field).
        local_key = jax.random.fold_in(local_key, lax.axis_index(axis_name))
    wire = simulate_wire_dtype(axis_size, compression)
    if wire is not None:
        shards = _fused_scatter_mean(
            grads, axis_name, compression, axis_size, local_key, wire
        )
    else:
        if compression.quantize_local:
            grads = apply_codec_fenced(fq, grads, compression, key=local_key)
        # Reduce-scatter the mean: chunk each leaf [N, K] and let replica r
        # keep the summed row r.  Division by the static axis size matches
        # pmean's.
        shards = jax.tree.map(
            lambda g: lax.psum_scatter(
                chunk_leaf(g.astype(jnp.float32), axis_size), axis_name,
                scatter_dimension=0, tiled=True,
            ) / axis_size,
            grads,
        )
    if compression.quantize_mean and compression.mode != "none":
        levels = float(levels_for(compression))
        out_dtype = jnp.int8 if compression.mode == "int8" else jnp.float16
        # Same fusion fence as apply_codec_fenced, cut at the same points
        # (chunk mean in, dequantized chunk mean out) so the per-element
        # quantization arithmetic compiles identically to the replicated
        # path's region.
        shards = lax.optimization_barrier(shards)
        # Global scale over this sync's tree (the whole model at
        # bucket_mb=0, one bucket otherwise), exactly global_absmax of the
        # full mean: padding rows are zero and max is order-independent.
        scale = lax.pmax(global_absmax(shards), axis_name)
        safe = safe_divisor(scale)
        mean_keys = _leaf_keys(shards, mean_key)

        def q_shard(shard, g_full, subkey):
            noise = None
            if subkey is not None:
                # Draw at the FULL leaf shape (same counters as the
                # replicated path's draw), then slice this replica's chunk.
                noise = local_chunk(
                    jax.random.uniform(subkey, g_full.shape),
                    axis_size,
                    axis_name,
                )
            scaled = shard.astype(jnp.float32) / safe * levels
            q = snap_to_lattice(scaled, levels, noise=noise).astype(out_dtype)
            # Single runtime-scalar multiply, exactly quantize.decode's
            # formula (constant-divisor division is not rewrite-stable
            # across programs — see decode's docstring).
            return q.astype(jnp.float32) * (scale / levels)

        shards = lax.optimization_barrier(
            jax.tree.map(q_shard, shards, grads, mean_keys)
        )
    return shards
