"""Synchronous gradient synchronization with optional lossy compression.

This is the TPU-native replacement for the reference's whole L4 protocol
(кластер.py:255-557): workers quantize accumulated grads and send to the
server; the server averages, re-quantizes the average, broadcasts; everyone
(server included, via its self-application block кластер.py:402-433) steps on
the *same* dequantized gradient.

Semantics preserved:
- optional per-replica quantization before the reduce (the worker wire);
- exact mean across all replicas — fixing the reference's "crooked averaging
  … (fix!)" loop that over-divides earlier contributions and divides by the
  worker count instead of the replica count (кластер.py:268-321, SURVEY §2.8d);
- optional re-quantization of the mean, so every replica applies a
  bit-identical update (SPMD + deterministic psum already guarantees
  identical values; re-quantization reproduces the reference's *information
  loss*, not its mechanism).

Runs inside shard_map over the ``data`` mesh axis: `lax.pmean` lowers to one
fused XLA all-reduce over ICI/DCN instead of N sequential pickled TCP
round-trips.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
from jax import lax

from ddlpc_tpu.config import CompressionConfig
from ddlpc_tpu.ops.quantize import fake_quantize, rounding_key

PyTree = Any


def resolve_codec_backend(compression: CompressionConfig):
    """The fake-quantize implementation for the simulate transport: the XLA
    tree transform, or the fused Pallas kernel (interpreted off-TPU so the
    CPU test meshes exercise the same code path)."""
    if compression.codec_backend == "pallas":
        from ddlpc_tpu.ops.pallas_quantize import (
            default_interpret,
            fake_quantize_pallas,
        )

        return functools.partial(
            fake_quantize_pallas, interpret=default_interpret()
        )
    if compression.codec_backend == "xla":
        return fake_quantize
    raise ValueError(
        f"unknown codec_backend {compression.codec_backend!r} "
        "(expected 'xla' or 'pallas')"
    )


def sync_gradients(
    grads: PyTree,
    axis_name: str,
    compression: CompressionConfig,
    axis_size: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> PyTree:
    """All-reduce-mean local gradients across ``axis_name``.

    Call inside shard_map/pmap.  With compression.mode='none' this is a plain
    pmean; otherwise the codec's information loss is injected at the same
    points the reference loses it (client send: quantize_local; server
    rebroadcast: quantize_mean).

    ``compression.transport='ring'`` swaps the fp32 pmean for the
    byte-compressed ppermute ring (compressed_allreduce.py), which needs the
    static ``axis_size`` of the mesh axis.

    ``key`` drives stochastic rounding (compression.rounding='stochastic');
    all replicas must pass the same key (the step builders derive it from
    the replicated step counter), which keeps the mean-requantization
    bit-identical across replicas.
    """
    if compression.transport not in ("simulate", "ring"):
        raise ValueError(
            f"unknown compression transport {compression.transport!r} "
            "(expected 'simulate' or 'ring')"
        )
    # Validate codec_backend up front on every path: the ring inlines its own
    # formula (backend-independent), but a typo'd backend must not be
    # silently accepted on one transport and rejected on the other.
    fq = resolve_codec_backend(compression)
    if compression.transport == "ring" and compression.mode != "none":
        if axis_size is None:
            raise ValueError(
                "transport='ring' needs the static axis_size (the step "
                "builders pass mesh.shape[data_axis])"
            )
        if not (compression.quantize_local and compression.quantize_mean):
            raise ValueError(
                "transport='ring' quantizes at both loss points by "
                "construction (integer wire sums + quantized gather hops); "
                "quantize_local/quantize_mean=False ablations need "
                "transport='simulate'"
            )
        from ddlpc_tpu.parallel.compressed_allreduce import (
            ring_allreduce_mean_quantized,
        )

        return ring_allreduce_mean_quantized(
            grads, axis_name, axis_size, compression, key=key
        )
    if compression.mode != "none":
        key = rounding_key(compression, key)
    local_key = mean_key = None
    if key is not None:
        local_key, mean_key = jax.random.split(key)
        # Decorrelate the LOCAL rounding noise across replicas: per-replica
        # gradients are highly correlated, so a shared draw would make the
        # rounding errors common-mode and survive the pmean at full-step
        # size instead of averaging down ~1/√N.  The MEAN key must stay
        # shared — every replica requantizes the identical mean and must
        # make identical decisions.
        local_key = jax.random.fold_in(local_key, lax.axis_index(axis_name))
    if compression.quantize_local:
        grads = fq(grads, compression, key=local_key)
    grads = lax.pmean(grads, axis_name)
    if compression.quantize_mean:
        grads = fq(grads, compression, key=mean_key)
    return grads
