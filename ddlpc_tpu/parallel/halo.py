"""Halo exchange for spatially-sharded convolutions.

The conv-segmentation analog of sequence/context parallelism: tiles too
large for one chip's HBM are sharded along H across the mesh ``space`` axis,
and each SAME-padded conv needs ``k//2`` boundary rows from the spatial
neighbors.  The reference has nothing like this — its only axis is data
parallelism over whole tiles (SURVEY §2 parallelism table); spatial sharding
is how this framework scales the reference's "bigger tiles" dimension
(кластер.py:737 fixes 512×512 because one GPU had to hold the whole tile).

Two layers of support:

- :func:`halo_exchange` — the explicit primitive for shard_map/Pallas code:
  one bidirectional ``lax.ppermute`` ring shift per direction.  Devices at
  the global edge receive zeros (ppermute's semantics for absent sources),
  which composes exactly with SAME zero-padding.
- The GSPMD path (parallel/train_step.py:make_train_step_gspmd) — for whole
  models, XLA's SPMD partitioner inserts these halo exchanges automatically
  for every conv when the input is sharded over ``space``; that is the
  recommended way to train spatially-sharded (this module's primitive is for
  hand-written kernels and for tests that pin down the semantics).

Status (explicit, per VERDICT r1 #8): this module is a SEMANTICS-PINNING
REFERENCE IMPLEMENTATION, not a production code path.  No model calls it;
models shard spatially through GSPMD.  It stays because (a)
tests/test_halo.py proves the ppermute ring exchange bit-matches both the
unsharded conv and what XLA's partitioner must produce — the executable
specification of the ``space`` axis — and (b) it is the building block any
future Pallas fused halo-conv kernel starts from; round-1 profiling showed
conv halo exchange is not a bottleneck, so such a kernel is not currently
justified.
"""

from __future__ import annotations

import jax
from jax import lax


def halo_exchange(
    x: jax.Array, axis_name: str, halo: int, spatial_axis: int = 1
) -> jax.Array:
    """Concatenate ``halo`` rows from each spatial neighbor onto this shard.

    x: the local shard, e.g. [N, H_local, W, C] with ``spatial_axis=1``.
    Returns [N, H_local + 2*halo, W, C]; the first/last shard's outer halo
    is zeros (global-boundary SAME padding).  Call inside shard_map over
    ``axis_name``.
    """
    if halo <= 0:
        return x
    from ddlpc_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    if x.shape[spatial_axis] < halo:
        raise ValueError(
            f"local spatial extent {x.shape[spatial_axis]} smaller than halo "
            f"{halo}; use fewer shards or larger tiles"
        )

    def take(start: bool, count: int) -> jax.Array:
        idx = [slice(None)] * x.ndim
        idx[spatial_axis] = slice(0, count) if start else slice(-count, None)
        return x[tuple(idx)]

    # Shard i sends its TOP rows to i-1 (their bottom halo) and its BOTTOM
    # rows to i+1 (their top halo).  Devices with no source receive zeros.
    to_prev = [(i, i - 1) for i in range(1, n)]
    to_next = [(i, i + 1) for i in range(n - 1)]
    from_next = lax.ppermute(take(True, halo), axis_name, to_prev)
    from_prev = lax.ppermute(take(False, halo), axis_name, to_next)
    return jax.numpy.concatenate([from_prev, x, from_next], axis=spatial_axis)


def sharded_same_conv(
    x: jax.Array,
    kernel: jax.Array,
    axis_name: str,
    spatial_axis: int = 1,
) -> jax.Array:
    """SAME conv over an H-sharded NHWC input: halo-exchange then slice.

    Reference semantics check for the primitive: inside shard_map over
    ``axis_name`` this equals the unsharded ``lax.conv_general_dilated``
    with SAME padding on the concatenated global array (tests/test_halo.py).
    kernel: [kh, kw, C_in, C_out]; both kernel dims must be odd (XLA SAME
    pads even kernels asymmetrically, which ``kw//2`` both-sides padding and
    the symmetric halo would silently get wrong).
    """
    kh, kw = kernel.shape[0], kernel.shape[1]
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError(
            f"sharded_same_conv requires odd kernel dims, got {(kh, kw)}"
        )
    halo = kh // 2
    padded = halo_exchange(x, axis_name, halo, spatial_axis)
    # H got VALID-cropped by the conv exactly where the halo was added; W
    # keeps SAME padding.
    return lax.conv_general_dilated(
        padded,
        kernel,
        window_strides=(1, 1),
        padding=((0, 0), (kernel.shape[1] // 2,) * 2),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
